"""Differential tests: JAX curve/scalar ops vs the pure-python ground truth."""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from firedancer_tpu.ops import curve as fc
from firedancer_tpu.ops import limbs as fl
from firedancer_tpu.ops import scalar as fs
from firedancer_tpu.ops.ref import ed25519_ref as ref

P = ref.P
L = ref.L


def bytes_cols(rows: list[bytes]) -> jnp.ndarray:
    """list of equal-length byte strings -> (len, B) int32 array."""
    return jnp.asarray(
        np.stack([np.frombuffer(r, dtype=np.uint8) for r in rows], axis=-1).astype(
            np.int32
        )
    )


def fe_ints(fe) -> list[int]:
    arr = np.asarray(fe)
    return [fl.limbs_to_int(arr[:, i]) for i in range(arr.shape[1])]


def points_from_jax(p):
    xs, ys, zs = fe_ints(p[0]), fe_ints(p[1]), fe_ints(p[2])
    out = []
    for x, y, z in zip(xs, ys, zs):
        zi = pow(z, P - 2, P)
        out.append((x * zi % P, y * zi % P))
    return out


def affine(p):
    x, y, z, _ = p
    zi = pow(z, P - 2, P)
    return (x * zi % P, y * zi % P)


def rand_points(rng, n):
    """n random points (python ref) plus torsion edge cases appended."""
    pts = []
    for i in range(n):
        k = int.from_bytes(rng.bytes(32), "little") % L
        pts.append(ref.point_mul(k or 1, ref.BASE))
    return pts


j_decompress = jax.jit(fc.point_decompress)
j_dbl = jax.jit(fc.point_dbl)
j_add = jax.jit(fc.point_add)
j_compress = jax.jit(fc.point_compress)
j_small = jax.jit(lambda b: fc.is_small_order(fc.point_decompress(b)[0]))
j_validate = jax.jit(fs.sc_validate)
j_reduce = jax.jit(fs.sc_reduce512)


@pytest.mark.slow  # ~25 s of XLA compiles; decompress stays covered in
# tier-1 by test_decompress_rejects_non_points + the sigverify suites
def test_decompress_compress_roundtrip(rng):
    pts = rand_points(rng, 12)
    enc = [ref.point_compress(p) for p in pts]
    jp, ok = j_decompress(bytes_cols(enc))
    assert np.asarray(ok).all()
    assert points_from_jax(jp) == [affine(p) for p in pts]
    out = np.asarray(j_compress(jp))
    expect = np.stack(
        [np.frombuffer(e, dtype=np.uint8) for e in enc], axis=-1
    )
    assert (out == expect).all()


def test_decompress_rejects_non_points(rng):
    # y values whose x^2 is non-square: find some by brute force
    bad = []
    v = 2
    while len(bad) < 6:
        enc = int.to_bytes(v, 32, "little")
        if ref.point_decompress(enc) is None:
            bad.append(enc)
        v += 1
    _, ok = j_decompress(bytes_cols(bad))
    assert not np.asarray(ok).any()


@pytest.mark.slow  # ~30 s of XLA compiles; dbl/add correctness rides
# the tier-1 sigverify differential suites transitively
def test_dbl_add_vs_ref(rng):
    pts = rand_points(rng, 8)
    enc = bytes_cols([ref.point_compress(p) for p in pts])
    jp, _ = j_decompress(enc)
    assert points_from_jax(j_dbl(jp)) == [
        affine(ref.point_double(p)) for p in pts
    ]
    pts2 = rand_points(rng, 8)
    enc2 = bytes_cols([ref.point_compress(p) for p in pts2])
    jq, _ = j_decompress(enc2)
    assert points_from_jax(j_add(jp, jq)) == [
        affine(ref.point_add(p, q)) for p, q in zip(pts, pts2)
    ]


def small_order_encodings() -> list[bytes]:
    """All 8-torsion y-encodings, derived analytically (no scanning):
    identity y=1, order-2 y=-1, order-4 y=0; order-8 points satisfy
    x^2 = -y^2, which with the curve equation gives d*y^4 + 2y^2 - 1 = 0,
    i.e. y^2 = (+-sqrt(1+d) - 1)/d."""

    def sqrt_mod(a):
        a %= P
        x = pow(a, (P + 3) // 8, P)
        if (x * x - a) % P:
            x = x * ref.SQRT_M1 % P
        return x if (x * x - a) % P == 0 else None

    ys = [0, 1, P - 1]
    s = sqrt_mod(1 + ref.D)
    assert s is not None
    for r in (s, P - s):
        y2 = (r - 1) * pow(ref.D, P - 2, P) % P
        y = sqrt_mod(y2)
        if y is not None:
            ys += [y, P - y]
    out = []
    for y in ys:
        enc = int.to_bytes(y, 32, "little")
        p = ref.point_decompress(enc)
        if p is not None and ref.is_small_order(p):
            out.append(enc)
    return out


@pytest.mark.slow  # heaviest compile in the file (~40 s on 1 core)
def test_small_order_detection(rng):
    # All 8-torsion encodings must flag; random honest points must not.
    found = small_order_encodings()
    assert len(found) >= 5
    honest = [ref.point_compress(p) for p in rand_points(rng, 5)]
    flags = np.asarray(j_small(bytes_cols(found + honest)))
    assert flags[: len(found)].all()
    assert not flags[len(found):].any()


def test_scalar_validate(rng):
    cases = [0, 1, L - 1, L, L + 1, 2**252, (1 << 256) - 1] + [
        int.from_bytes(rng.bytes(32), "little") for _ in range(9)
    ]
    enc = bytes_cols([int.to_bytes(v, 32, "little") for v in cases])
    got = list(np.asarray(j_validate(enc)))
    assert got == [v < L for v in cases]


def test_scalar_reduce512(rng):
    cases = [0, 1, L, L - 1, 2**252, (1 << 512) - 1] + [
        int.from_bytes(rng.bytes(64), "little") for _ in range(10)
    ]
    enc = bytes_cols([int.to_bytes(v, 64, "little") for v in cases])
    out = np.asarray(j_reduce(enc))
    got = [fs.limbs_to_int(out[:, i]) for i in range(len(cases))]
    assert got == [v % L for v in cases]


@pytest.mark.slow  # jit-compiles the full double-scalar-mult (~2 min)
def test_double_scalar_mul_base(rng):
    # [s]B + [k]A vs python ref, including k or s = 0 edge cases
    ks = [int.from_bytes(rng.bytes(32), "little") % L for _ in range(6)] + [0, 1]
    ss = [int.from_bytes(rng.bytes(32), "little") % L for _ in range(6)] + [1, 0]
    pts = rand_points(rng, 8)
    enc = bytes_cols([ref.point_compress(p) for p in pts])

    @jax.jit
    def run(kb, sb, penc):
        a, _ = fc.point_decompress(penc)
        return fc.double_scalar_mul_base(kb, a, sb)

    def sc(vals):
        return fs.sc_frombytes(
            bytes_cols([int.to_bytes(v, 32, "little") for v in vals])
        )

    kb = jax.jit(fs.sc_bits)(sc(ks))
    sb = jax.jit(fs.sc_bits)(sc(ss))
    got = points_from_jax(run(kb, sb, enc))
    expect = [
        affine(ref.point_add(ref.point_mul(s, ref.BASE), ref.point_mul(k, p)))
        for k, s, p in zip(ks, ss, pts)
    ]
    assert got == expect


@pytest.mark.slow  # compiles BOTH scalar-mult paths (~100 s on 1 core)
def test_windowed_matches_ladder(rng):
    """Differential: the windowed fast path == the 1-bit Shamir ladder on
    random (k, s, A) triples (both must equal the host ref, but checking
    them against each other catches shared-helper regressions too)."""
    ks = [int.from_bytes(rng.bytes(32), "little") % L for _ in range(4)]
    ss = [int.from_bytes(rng.bytes(32), "little") % L for _ in range(4)]
    pts = rand_points(rng, 4)
    enc = bytes_cols([ref.point_compress(p) for p in pts])

    def sc(vals):
        return fs.sc_frombytes(
            bytes_cols([int.to_bytes(v, 32, "little") for v in vals])
        )

    kb = jax.jit(fs.sc_bits)(sc(ks))
    sb = jax.jit(fs.sc_bits)(sc(ss))
    a, _ = jax.jit(fc.point_decompress)(enc)
    fast = points_from_jax(jax.jit(fc.double_scalar_mul_base)(kb, a, sb))
    slow = points_from_jax(jax.jit(fc.double_scalar_mul_base_ladder)(kb, a, sb))
    assert fast == slow  # affine (x, y) pairs
