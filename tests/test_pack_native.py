"""Differential tests: the native pack scheduler + fused dedup lane vs
the Python lane.

The contract (ISSUE 9): across seeded adversarial workloads —
conflicting writers, ALT lock collisions, vote floods, limit-boundary
costs, duplicate signatures, malformed compute-budget instructions —
the native lane (native/fd_pack.cpp via pack/scheduler_native.py) must
emit BYTE-IDENTICAL microblock frames, make identical eviction
decisions, keep identical end_block accounting, and drop the identical
dedup set as pack/scheduler.Pack behind DedupStage+PackStage.

The whole module SKIPS (never fails) when the native lane is
unavailable (no toolchain, .so deleted, or FDTPU_NATIVE_PACK=0).
"""

from __future__ import annotations

import hashlib
import random

import pytest

from firedancer_tpu.pack import scheduler_native as sn

if not sn.available():  # pragma: no cover - toolchain-less host
    pytest.skip("native pack lane unavailable", allow_module_level=True)

from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.pack import cost as fc
from firedancer_tpu.pack.scheduler import BlockLimits, Pack
from firedancer_tpu.protocol import txn as ft
from firedancer_tpu.runtime.verify import encode_verified, sig_tag

BH = hashlib.sha256(b"pack-native-bh").digest()


def _keypair(tag: bytes):
    s = hashlib.sha256(tag).digest()
    return s, ref.public_key(s)


PAYERS = [_keypair(b"pnp%d" % i) for i in range(8)]
DESTS = [hashlib.sha256(b"pnd%d" % i).digest() for i in range(5)]
TABLES = [hashlib.sha256(b"lut%d" % i).digest() for i in range(3)]
VOTE_ACCTS = [hashlib.sha256(b"pnv%d" % i).digest() for i in range(3)]


def _sign_txn(sec, msg):
    return ft.txn_assemble([ref.sign(sec, msg)], msg)


def _transfer(rng, *, payer=None, dest=None, cb=(), lamports=None,
              extra_ro=0):
    sec, pub = PAYERS[payer if payer is not None else rng.randrange(8)]
    d = DESTS[dest if dest is not None else rng.randrange(5)]
    accts = [pub, d, ft.SYSTEM_PROGRAM]
    instrs = []
    if cb:
        accts.append(fc.COMPUTE_BUDGET_PROGRAM)
        instrs += [ft.InstrSpec(program_id=3, accounts=b"", data=x)
                   for x in cb]
    instrs.append(ft.InstrSpec(
        program_id=2, accounts=bytes([0, 1]),
        data=(2).to_bytes(4, "little")
        + (lamports if lamports is not None
           else rng.randrange(1, 1000)).to_bytes(8, "little")))
    msg = ft.message_build(
        version=ft.VLEGACY, signature_cnt=1, readonly_signed_cnt=0,
        readonly_unsigned_cnt=len(accts) - 2, acct_addrs=accts,
        recent_blockhash=BH, instrs=instrs)
    return _sign_txn(sec, msg)


def _lut_txn(rng, table_i):
    """v0 txn loading from a shared lookup table: the table ADDRESS
    write-locks, so two of these serialize (ALT lock collision)."""
    sec, pub = PAYERS[rng.randrange(8)]
    accts = [pub, ft.SYSTEM_PROGRAM]
    msg = ft.message_build(
        version=ft.V0, signature_cnt=1, readonly_signed_cnt=0,
        readonly_unsigned_cnt=1, acct_addrs=accts, recent_blockhash=BH,
        instrs=[ft.InstrSpec(program_id=1, accounts=b"", data=b"\x09")],
        luts=[ft.LutSpec(table_addr=TABLES[table_i],
                         writable=bytes([rng.randrange(4)]),
                         readonly=b"")])
    return _sign_txn(sec, msg)


def _vote(rng, i):
    sec, _pub = PAYERS[i % 8]
    va = VOTE_ACCTS[i % len(VOTE_ACCTS)]
    return ft.vote_txn(sec, va, 100 + i, BH,
                       bank_hash=hashlib.sha256(b"vbh").digest())


def _cb_price(p):
    return (3).to_bytes(1, "little") + p.to_bytes(8, "little")


def _cb_cu(cu):
    return (2).to_bytes(1, "little") + cu.to_bytes(4, "little")


def _workload(rng, n):
    """The adversarial mix; returns payloads (some deliberately equal =
    duplicate signatures)."""
    out = []
    for i in range(n):
        r = rng.random()
        if r < 0.22:
            out.append(_vote(rng, i))
        elif r < 0.35:
            # conflicting writers: a hot destination account
            out.append(_transfer(rng, dest=0))
        elif r < 0.45:
            out.append(_lut_txn(rng, rng.randrange(len(TABLES))))
        elif r < 0.65:
            # priority-fee spread incl. u64-scale prices (rewards must
            # compare exactly, not in floats)
            cb = [_cb_cu(rng.choice([1, 300, 200_000, 1_400_000])),
                  _cb_price(rng.choice([0, 1, 999_999, 10**6, 2**40,
                                        2**63]))]
            out.append(_transfer(rng, cb=cb))
        elif r < 0.72 and out:
            out.append(rng.choice(out))  # duplicate signature
        elif r < 0.78:
            # malformed compute budget: both lanes must DROP it
            bad = rng.choice([
                b"\x02\x01",                       # truncated
                _cb_cu(5) + b"x",                  # wrong size
                (9).to_bytes(1, "little") * 5,     # unknown tag
                (1).to_bytes(1, "little") + (31).to_bytes(4, "little"),
            ])
            out.append(_transfer(rng, cb=[bad]))
        else:
            out.append(_transfer(rng))
    return out


class _Lanes:
    """Drives both lanes through identical op sequences and compares.

    The python side replicates the DedupStage -> PackStage composition:
    the tag goes through a TCache first (duplicates dropped before pack
    sees them), then Pack.insert; the native side does both inside ONE
    fd_pack_insert_burst crossing.
    """

    def __init__(self, *, bank_cnt=3, depth=64, max_txn_per_microblock=9,
                 limits=None, tcache_depth=128):
        from firedancer_tpu.tango.rings import TCache
        from firedancer_tpu.tango.tcache_native import NativeTCache

        self.py = Pack(bank_cnt=bank_cnt, depth=depth,
                       max_txn_per_microblock=max_txn_per_microblock,
                       limits=limits)
        self.nat = sn.NativePack(bank_cnt=bank_cnt, depth=depth,
                                 max_txn_per_microblock=max_txn_per_microblock,
                                 limits=limits)
        self.py_tcache = TCache(tcache_depth)
        self.nat.attach_tcache(NativeTCache(tcache_depth))
        self.bank_cnt = bank_cnt
        self.mb_seq = 0
        self.frames = []
        self.py_drops = []   # (index, reason) of python-lane drops
        self.nat_drops = []

    def insert(self, i, payload):
        t = ft.txn_parse(payload)
        assert t is not None
        frag = encode_verified(payload, t)
        tag = sig_tag(t.signatures(payload)[0])
        # python lane: dedup stage first, then pack
        if self.py_tcache.insert(tag):
            py_ok, py_reason = False, "dup"
        else:
            py_ok = self.py.insert(payload, t)
            py_reason = None if py_ok else "drop"
        code = self.nat.insert_burst([(frag, tag, 7_000 + i)])[0]
        nat_ok = code == sn.INS_OK
        nat_reason = (None if nat_ok
                      else "dup" if code == sn.INS_DUP else "drop")
        assert (py_ok, py_reason) == (nat_ok, nat_reason), (
            i, py_reason, code)
        if not py_ok:
            self.py_drops.append((i, py_reason))
            self.nat_drops.append((i, nat_reason))

    def schedule(self, bank, votes=False):
        chosen = self.py.schedule_next_microblock(bank, votes=votes)
        res = self.nat.schedule(bank, votes=votes, mb_seq=self.mb_seq)
        if not chosen:
            assert res is None, ("native scheduled, python did not",
                                 bank, votes, res and res[1])
            return False
        frame = self.mb_seq.to_bytes(4, "little")
        frame += len(chosen).to_bytes(2, "little")
        for o in chosen:
            f = encode_verified(o.payload, o.desc)
            frame += len(f).to_bytes(2, "little") + f
        assert res is not None, ("python scheduled, native did not",
                                 bank, votes, len(chosen))
        assert res[0] == frame, ("frame mismatch", bank, votes)
        assert res[1] == len(chosen)
        assert res[2] == sum(o.cost.total for o in chosen)
        self.frames.append(frame)
        self.mb_seq += 1
        return True

    def done(self, bank):
        self.py.microblock_done(bank)
        self.nat.microblock_done(bank)

    def end_block(self):
        self.py.end_block()
        self.nat.end_block()
        self.check_accounting()

    def check_accounting(self):
        assert (
            self.py.cost_used,
            self.py.vote_cost_used,
            self.py.data_bytes_used,
        ) == self.nat.block_state()
        assert self.py.pending_cnt() == self.nat.pending_cnt()


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_randomized_streams_identical(seed):
    """The headline differential: a seeded adversarial workload with
    interleaved schedule/done/end_block ops produces byte-identical
    microblock streams, identical drops, identical accounting."""
    rng = random.Random(seed)
    lanes = _Lanes(depth=48, max_txn_per_microblock=7)
    for i, p in enumerate(_workload(rng, 300)):
        lanes.insert(i, p)
        r = rng.random()
        if r < 0.35:
            lanes.schedule(rng.randrange(lanes.bank_cnt),
                           votes=rng.random() < 0.3)
        if r < 0.25:
            lanes.done(rng.randrange(lanes.bank_cnt))
        if rng.random() < 0.03:
            lanes.end_block()
    # drain everything schedulable
    for _ in range(200):
        progressed = False
        for b in range(lanes.bank_cnt):
            progressed |= lanes.schedule(b)
            progressed |= lanes.schedule(b, votes=True)
            lanes.done(b)
        if not progressed:
            break
    lanes.check_accounting()
    assert lanes.frames, "workload scheduled nothing"
    assert lanes.py_drops == lanes.nat_drops
    assert any(r == "dup" for _, r in lanes.py_drops), "no dedup coverage"


def test_limit_boundary_costs():
    """Tight block limits: every limit (total, vote, per-writer, data
    bytes) binds mid-stream and both lanes agree on the exact txn where
    it trips — including within-microblock accumulation."""
    rng = random.Random(99)
    limits = BlockLimits(
        max_cost_per_block=40_000,
        max_vote_cost_per_block=9_000,
        max_write_cost_per_acct=8_000,
        max_data_bytes_per_block=6_000,
    )
    lanes = _Lanes(bank_cnt=2, depth=64, max_txn_per_microblock=31,
                   limits=limits)
    for i, p in enumerate(_workload(rng, 150)):
        lanes.insert(i, p)
        if rng.random() < 0.3:
            lanes.schedule(rng.randrange(2), votes=rng.random() < 0.4)
        if rng.random() < 0.2:
            lanes.done(rng.randrange(2))
        if rng.random() < 0.1:
            lanes.end_block()
    lanes.check_accounting()


def test_eviction_parity_small_pool():
    """depth=8 pool under a 150-txn flood: the delete-worst rule (both
    pools' tails considered, ratio-only compare, ties keep the
    incumbent) decides identically in both lanes."""
    rng = random.Random(5)
    lanes = _Lanes(bank_cnt=2, depth=8)
    for i, p in enumerate(_workload(rng, 150)):
        lanes.insert(i, p)
    lanes.check_accounting()
    # what remains schedules identically
    while lanes.schedule(0) or lanes.schedule(0, votes=True):
        lanes.done(0)
    lanes.check_accounting()


def test_vote_flood_separate_pool():
    """An all-vote flood lands in the vote pool and schedules only via
    votes=True, identically in both lanes."""
    lanes = _Lanes(bank_cnt=2, depth=32)
    rng = random.Random(11)
    for i in range(40):
        lanes.insert(i, _vote(rng, i))
    assert not lanes.schedule(0)          # non-vote pool is empty
    assert lanes.schedule(0, votes=True)  # the vote pool is not
    lanes.check_accounting()


def test_alt_lock_collision_serializes():
    """Two v0 txns loading from the SAME table conflict (the table
    address write-locks); both lanes schedule them one-per-microblock."""
    rng = random.Random(3)
    lanes = _Lanes(bank_cnt=2, depth=16)
    lanes.insert(0, _lut_txn(rng, 0))
    lanes.insert(1, _lut_txn(rng, 0))
    assert lanes.schedule(0)
    assert lanes.frames[-1][4:6] == (1).to_bytes(2, "little"), \
        "ALT twins must not share a microblock"
    # the second only schedules after the first bank's locks release
    assert not lanes.schedule(1)
    lanes.done(0)
    assert lanes.schedule(1)
    lanes.check_accounting()


def test_cost_model_fuzz_vs_python():
    """The native cost model (fd_pack_cost_probe) agrees with
    pack/cost.compute_cost — total cost, exact rewards (u128 priority
    fees included), simple-vote detection, malformed-CBP rejection —
    across the randomized workload."""
    rng = random.Random(77)
    n_reject = 0
    for p in _workload(rng, 250):
        t = ft.txn_parse(p)
        packed = ft.txn_pack(t)
        rc, totals, is_vote = sn.cost_probe(p, packed)
        c = fc.compute_cost(p, t)
        if c is None:
            assert rc == -2, "python rejected, native accepted"
            n_reject += 1
            continue
        assert rc == 0, "native rejected, python accepted"
        assert totals == (c.total, c.rewards(t.signature_cnt))
        assert is_vote == c.is_simple_vote
    assert n_reject > 0, "no malformed-CBP coverage"


def test_stage_streams_identical():
    """Stage-level differential: the SAME verified-frag stream (with
    duplicates) through DedupStage->PackStage vs the fused
    NativePackStage publishes byte-identical microblock frames."""
    from firedancer_tpu.runtime.dedup import DedupStage
    from firedancer_tpu.runtime.pack_stage import NativePackStage, PackStage
    from firedancer_tpu.tango import shm

    rng = random.Random(21)
    payloads = _workload(rng, 80)

    def run_lane(native: bool):
        uid = f"pn{random.randrange(1 << 30)}"
        links = []

        def mk(name, mtu=4096, depth=256):
            link = shm.ShmLink.create(f"fdtpu_{uid}_{name}", depth=depth,
                                      mtu=mtu)
            links.append(link)
            return link

        vd, bd, pb = mk("vd"), mk("bd", mtu=64), mk("pb", mtu=65536)
        feeder = shm.Producer(vd)
        stages = []
        # scheduling is held back (min_pending > stream size, adaptive
        # close off) until EVERY frag is pooled, so both lanes schedule
        # from the identical pool state — the comparison is about the
        # scheduler, not about sweep phasing between 1- and 2-stage
        # topologies
        policy = dict(bank_cnt=1, min_pending=10**9, mb_deadline_s=3600.0,
                      adaptive=False)
        if native:
            pack = NativePackStage(
                "pack", ins=[shm.Consumer(vd), shm.Consumer(bd)],
                outs=[shm.Producer(pb)], **policy)
            stages = [pack]
        else:
            dp = mk("dp")
            dedup = DedupStage("dedup", ins=[shm.Consumer(vd)],
                               outs=[shm.Producer(dp)])
            pack = PackStage(
                "pack", ins=[shm.Consumer(dp), shm.Consumer(bd)],
                outs=[shm.Producer(pb)], **policy)
            stages = [dedup, pack]
        done = shm.Producer(bd)
        sink = shm.Consumer(pb)
        frames = []
        try:
            for p in payloads:
                t = ft.txn_parse(p)
                feeder.try_publish(encode_verified(p, t),
                                   sig=sig_tag(t.signatures(p)[0]),
                                   tsorig=1)
            for _ in range(200):  # intake only: nothing schedules yet
                for s in stages:
                    s.run_once()
            assert not sink.has_pending()
            pack.flush()
            for _ in range(5000):
                for s in stages:
                    s.run_once()
                res = sink.poll()
                if res not in (shm.POLL_EMPTY, shm.POLL_OVERRUN):
                    frames.append(res[1])
                    done.try_publish(b"", sig=0)  # release the bank lock
                elif not pack._pending_cnt():
                    break
            report = dict(pack.metrics.counters)
            if not native:
                report["dedup_dup"] = stages[0].metrics.get("dedup_dup")
        finally:
            for s in stages:
                s.ins = []
                s.outs = []
            feeder.link = None
            import gc

            gc.collect()
            for link in links:
                link.close()
                link.unlink()
        return frames, report

    py_frames, py_rep = run_lane(False)
    nat_frames, nat_rep = run_lane(True)
    assert py_frames, "python lane emitted nothing"
    assert py_frames == nat_frames
    assert py_rep["txn_in"] == nat_rep["txn_in"]
    assert py_rep["txn_scheduled"] == nat_rep["txn_scheduled"]
    assert py_rep["cu_consumed"] == nat_rep["cu_consumed"]
    assert py_rep["dedup_dup"] == nat_rep["dedup_dup"] > 0


def test_env_switch_disables(monkeypatch):
    monkeypatch.setenv(sn.ENV_SWITCH, "0")
    assert not sn.available()
    monkeypatch.delenv(sn.ENV_SWITCH)
    assert sn.available()
