"""JSON-RPC server tests: the bencho poll methods served from live
state (funk balances, txn counts, slots), protocol error handling."""

import hashlib

import pytest

from firedancer_tpu.flamenco.runtime import acct_build
from firedancer_tpu.funk import Funk
from firedancer_tpu.protocol.base58 import b58_encode
from firedancer_tpu.runtime.rpc import PipelineView, RpcServer, rpc_call


class _FakeBank:
    def __init__(self, n):
        from firedancer_tpu.runtime.stage import Metrics

        self.metrics = Metrics()
        self.metrics.inc("txn_exec", n)


class _FakePipe:
    def __init__(self):
        self.banks = [_FakeBank(70), _FakeBank(50)]

        class _S:  # shred stage stand-in
            slot = 42

        self.shred = _S()


@pytest.fixture
def server():
    funk = Funk()
    pub = hashlib.sha256(b"rpc-acct").digest()
    funk.rec_insert(None, pub, acct_build(123_456))
    view = PipelineView(pipeline=_FakePipe(), funk=funk)
    srv = RpcServer(view)
    yield srv, pub
    srv.close()


def test_bencho_methods(server):
    srv, pub = server
    assert rpc_call(srv.addr, "getHealth")["result"] == "ok"
    assert rpc_call(srv.addr, "getTransactionCount")["result"] == 120
    assert rpc_call(srv.addr, "getSlot")["result"] == 42
    r = rpc_call(srv.addr, "getBalance", [b58_encode(pub)])
    assert r["result"]["value"] == 123_456
    assert r["result"]["context"]["slot"] == 42


def test_errors(server):
    srv, _ = server
    r = rpc_call(srv.addr, "getBlockProduction")
    assert r["error"]["code"] == -32601
    r = rpc_call(srv.addr, "getBalance")  # missing param
    assert r["error"]["code"] == -32602
    r = rpc_call(srv.addr, "getBalance", ["not-base58!!"])
    assert r["error"]["code"] == -32603
    # unknown account -> zero balance, not an error
    other = hashlib.sha256(b"nobody").digest()
    assert rpc_call(srv.addr, "getBalance", [b58_encode(other)])["result"][
        "value"
    ] == 0


def test_bencho_style_rate_poll(server):
    """The bencho loop: poll getTransactionCount twice, diff / dt."""
    srv, _ = server
    c1 = rpc_call(srv.addr, "getTransactionCount")["result"]
    # pipeline commits more txns between polls
    srv.view.pipeline.banks[0].metrics.inc("txn_exec", 30)
    c2 = rpc_call(srv.addr, "getTransactionCount")["result"]
    assert c2 - c1 == 30
