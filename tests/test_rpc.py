"""JSON-RPC server tests: the bencho poll methods served from live
state (funk balances, txn counts, slots), protocol error handling."""

import hashlib

import pytest

from firedancer_tpu.flamenco.runtime import acct_build
from firedancer_tpu.funk import Funk
from firedancer_tpu.protocol.base58 import b58_encode
from firedancer_tpu.runtime.rpc import PipelineView, RpcServer, rpc_call


class _FakeBank:
    def __init__(self, n):
        from firedancer_tpu.runtime.stage import Metrics

        self.metrics = Metrics()
        self.metrics.inc("txn_exec", n)


class _FakePipe:
    def __init__(self):
        self.banks = [_FakeBank(70), _FakeBank(50)]

        class _S:  # shred stage stand-in
            slot = 42

        self.shred = _S()


@pytest.fixture
def server():
    funk = Funk()
    pub = hashlib.sha256(b"rpc-acct").digest()
    funk.rec_insert(None, pub, acct_build(123_456))
    view = PipelineView(pipeline=_FakePipe(), funk=funk)
    srv = RpcServer(view)
    yield srv, pub
    srv.close()


def test_bencho_methods(server):
    srv, pub = server
    assert rpc_call(srv.addr, "getHealth")["result"] == "ok"
    assert rpc_call(srv.addr, "getTransactionCount")["result"] == 120
    assert rpc_call(srv.addr, "getSlot")["result"] == 42
    r = rpc_call(srv.addr, "getBalance", [b58_encode(pub)])
    assert r["result"]["value"] == 123_456
    assert r["result"]["context"]["slot"] == 42


def test_errors(server):
    srv, _ = server
    r = rpc_call(srv.addr, "getBlockProduction")
    assert r["error"]["code"] == -32601
    r = rpc_call(srv.addr, "getBalance")  # missing param
    assert r["error"]["code"] == -32602
    # malformed base58 is the CLIENT's fault: invalid params, not -32603
    # (r4 review finding — clients retry on server faults)
    r = rpc_call(srv.addr, "getBalance", ["not-base58!!"])
    assert r["error"]["code"] == -32602
    # unknown account -> zero balance, not an error
    other = hashlib.sha256(b"nobody").digest()
    assert rpc_call(srv.addr, "getBalance", [b58_encode(other)])["result"][
        "value"
    ] == 0


def test_bencho_style_rate_poll(server):
    """The bencho loop: poll getTransactionCount twice, diff / dt."""
    srv, _ = server
    c1 = rpc_call(srv.addr, "getTransactionCount")["result"]
    # pipeline commits more txns between polls
    srv.view.pipeline.banks[0].metrics.inc("txn_exec", 30)
    c2 = rpc_call(srv.addr, "getTransactionCount")["result"]
    assert c2 - c1 == 30


@pytest.fixture
def wallet_server():
    """A server wired with the wallet-facing state: status cache,
    blockstore, faucet, submit sink."""
    import base64

    from firedancer_tpu.flamenco.blockstore import Blockstore, StatusCache
    from firedancer_tpu.flamenco import bpf_loader as bl

    funk = Funk()
    pub = hashlib.sha256(b"rpc-w-acct").digest()
    funk.rec_insert(
        None, pub,
        acct_build(777, data=b"hello-data", owner=bl.UPGRADEABLE_LOADER_PROGRAM),
    )
    sc = StatusCache()
    bh = hashlib.sha256(b"rpc-bh").digest()
    sc.register_blockhash(bh, 40)
    sig = b"G" * 64
    sc.insert(bh, sig, 41)
    submitted = []
    view = PipelineView(
        pipeline=_FakePipe(), funk=funk, status_cache=sc,
        submit_fn=lambda t: submitted.append(t) or True,
        genesis_hash_fn=lambda: hashlib.sha256(b"gen").digest(),
    )
    srv = RpcServer(view)
    yield srv, pub, bh, sig, submitted
    srv.close()


def test_wallet_methods(wallet_server):
    import base64

    from firedancer_tpu.flamenco import bpf_loader as bl
    from firedancer_tpu.flamenco.blockstore import MAX_BLOCKHASH_AGE
    from firedancer_tpu.protocol.base58 import b58_encode32

    srv, pub, bh, sig, _ = wallet_server
    # getAccountInfo: full account shape, base64 data
    r = rpc_call(srv.addr, "getAccountInfo", [b58_encode(pub)])["result"]
    assert r["value"]["lamports"] == 777
    assert base64.b64decode(r["value"]["data"][0]) == b"hello-data"
    assert r["value"]["owner"] == b58_encode32(bl.UPGRADEABLE_LOADER_PROGRAM)
    # absent account -> null value
    none = rpc_call(srv.addr, "getAccountInfo",
                    [b58_encode(hashlib.sha256(b"absent").digest())])
    assert none["result"]["value"] is None
    # getLatestBlockhash + validity
    r = rpc_call(srv.addr, "getLatestBlockhash")["result"]["value"]
    assert r["blockhash"] == b58_encode32(bh)
    assert r["lastValidBlockHeight"] == 40 + MAX_BLOCKHASH_AGE
    assert rpc_call(srv.addr, "isBlockhashValid",
                    [b58_encode32(bh)])["result"]["value"] is True
    # getSignatureStatuses: one hit, one miss
    r = rpc_call(
        srv.addr, "getSignatureStatuses",
        [[b58_encode(sig), b58_encode(b"Z" * 64)]],
    )["result"]["value"]
    assert r[0]["slot"] == 41 and r[1] is None
    # getVersion / getGenesisHash / getEpochInfo / misc
    assert "firedancer-tpu" in rpc_call(srv.addr, "getVersion")["result"]
    assert rpc_call(srv.addr, "getGenesisHash")["result"] == b58_encode32(
        hashlib.sha256(b"gen").digest()
    )
    info = rpc_call(srv.addr, "getEpochInfo")["result"]
    assert info["absoluteSlot"] == 42 and info["transactionCount"] == 120
    assert rpc_call(srv.addr, "getBlockHeight")["result"] == 42
    assert rpc_call(srv.addr,
                    "getMinimumBalanceForRentExemption", [100])["result"] > 0


def test_send_transaction(wallet_server):
    import base64

    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.protocol import txn as ft

    srv, pub, bh, _, submitted = wallet_server
    secret = hashlib.sha256(b"rpc-sender").digest()
    txn = ft.transfer_txn(secret, pub, 5, bh)
    r = rpc_call(srv.addr, "sendTransaction",
                 [base64.b64encode(txn).decode(), {"encoding": "base64"}])
    t = ft.txn_parse(txn)
    assert r["result"] == b58_encode(t.signatures(txn)[0])
    assert submitted == [txn]
    # garbage payloads are the client's error
    bad = rpc_call(srv.addr, "sendTransaction",
                   [base64.b64encode(b"junk").decode(),
                    {"encoding": "base64"}])
    assert bad["error"]["code"] == -32602


def test_wallet_surface_extended(server):
    """Round-4 methods: identity/leaders/votes/cluster/epoch/fees."""
    import base64

    from firedancer_tpu.flamenco.runtime import LAMPORTS_PER_SIGNATURE
    from firedancer_tpu.protocol.base58 import b58_encode32
    from firedancer_tpu.protocol import wsample

    srv, pub = server
    me = hashlib.sha256(b"identity").digest()
    voter = hashlib.sha256(b"voter").digest()
    srv.view.identity_fn = lambda: me
    srv.view.stakes_fn = lambda: {voter: 7_000}
    srv.view.leaders = wsample.epoch_leaders(
        0, 0, 64, [(voter, 7_000)]
    )
    srv.view.snapshot_slot_fn = lambda: 40
    srv.view.perf_samples = [
        {"slot": 41, "numTransactions": 100, "samplePeriodSecs": 60},
        {"slot": 42, "numTransactions": 120, "samplePeriodSecs": 60},
    ]

    assert rpc_call(srv.addr, "getIdentity")["result"]["identity"] == \
        b58_encode32(me)
    assert rpc_call(srv.addr, "getSlotLeader", [3])["result"] == \
        b58_encode32(voter)
    sched = rpc_call(srv.addr, "getLeaderSchedule")["result"]
    assert sched == {b58_encode32(voter): list(range(64))}
    votes = rpc_call(srv.addr, "getVoteAccounts")["result"]
    assert votes["current"][0]["votePubkey"] == b58_encode32(voter)
    assert votes["current"][0]["activatedStake"] == 7_000
    es = rpc_call(srv.addr, "getEpochSchedule")["result"]
    assert es["slotsPerEpoch"] == 432_000
    assert rpc_call(srv.addr, "getClusterNodes")["result"] == []
    multi = rpc_call(srv.addr, "getMultipleAccounts",
                     [[b58_encode(pub), b58_encode(bytes(32))]])
    vals = multi["result"]["value"]
    assert vals[0]["lamports"] == 123_456 and vals[1] is None
    msg = bytes([2]) + bytes(40)  # 2-signature message prefix
    fee = rpc_call(srv.addr, "getFeeForMessage",
                   [base64.b64encode(msg).decode()])
    assert fee["result"]["value"] == 2 * LAMPORTS_PER_SIGNATURE
    assert rpc_call(srv.addr, "minimumLedgerSlot")["result"] == 0
    snap = rpc_call(srv.addr, "getHighestSnapshotSlot")["result"]
    assert snap["full"] == 40
    perf = rpc_call(srv.addr, "getRecentPerformanceSamples", [1])["result"]
    assert perf == [{"slot": 42, "numTransactions": 120,
                     "samplePeriodSecs": 60}]


def test_server_fault_is_not_invalid_params(server):
    """A handler bug must report -32603 (retryable server fault), not
    -32602 — only the parameter-decode boundary maps to -32602."""
    srv, _pub = server

    def boom():
        raise KeyError("internal state bug")

    srv.view.identity_fn = boom
    r = rpc_call(srv.addr, "getIdentity")
    assert r["error"]["code"] == -32603
    # while an actually-bad param still maps to -32602
    r2 = rpc_call(srv.addr, "getBalance", ["!!not-base58!!"])
    assert r2["error"]["code"] == -32602


# -- block surface + pubsub (round-5: getBlock family, websockets) -----------


def _entry_frame(num_hashes, poh_hash, txns):
    from firedancer_tpu.runtime.poh_stage import build_entry

    return build_entry(num_hashes, poh_hash, txns)


@pytest.fixture
def block_server(tmp_path):
    """A server over a REAL blockstore holding slot 9: two transfers in
    one entry plus a tick."""
    from firedancer_tpu.flamenco.blockstore import Blockstore, StatusCache
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.protocol import txn as ft
    from firedancer_tpu.runtime import shredder as fsh

    secret = hashlib.sha256(b"rpc-payer").digest()
    payer = ref.public_key(secret)
    bh = hashlib.sha256(b"rpc-blockhash").digest()
    t1 = ft.transfer_txn(secret, b"d1" * 16, 5, bh, from_pubkey=payer)
    t2 = ft.transfer_txn(secret, b"d2" * 16, 6, bh, from_pubkey=payer)
    e1 = _entry_frame(1, hashlib.sha256(b"e1").digest(), [t1, t2])
    e2 = _entry_frame(3, hashlib.sha256(b"e2").digest(), [])
    batch = b"".join(
        len(e).to_bytes(4, "little") + e for e in (e1, e2)
    )
    leader_secret = hashlib.sha256(b"rpc-leader").digest()
    sh = fsh.Shredder(signer=lambda root: ref.sign(leader_secret, root))
    sets = sh.entry_batch_to_fec_sets(
        batch, slot=9, meta=fsh.EntryBatchMeta(block_complete=True))
    bs = Blockstore(str(tmp_path / "bs.log"))
    for st in sets:
        for buf in st.data_shreds:
            bs.insert_shred(buf)
    sc = StatusCache()
    sig1 = ft.txn_parse(t1).signatures(t1)[0]
    sc.insert(bh, sig1, 9)
    view = PipelineView(pipeline=_FakePipe(), blockstore=bs,
                        status_cache=sc)
    srv = RpcServer(view)
    yield srv, payer, t1, t2, sig1
    srv.close()
    bs.close()


def test_get_block_and_blocks(block_server):
    import base64

    srv, payer, t1, t2, _sig = block_server
    blk = rpc_call(srv.addr, "getBlock", [9])["result"]
    assert blk["parentSlot"] == 8
    got = [base64.b64decode(tx["transaction"][0])
           for tx in blk["transactions"]]
    assert got == [t1, t2]
    assert blk["transactions"][0]["meta"]["fee"] == 5000
    assert rpc_call(srv.addr, "getBlocks", [0])["result"] == [9]
    assert rpc_call(srv.addr, "getBlocks", [10])["result"] == []
    assert rpc_call(srv.addr, "getBlocksWithLimit", [0, 1])["result"] == [9]
    # a missing slot is the typed -32007 error
    err = rpc_call(srv.addr, "getBlock", [1234])["error"]
    assert err["code"] == -32007


def test_get_transaction_and_signatures_for_address(block_server):
    import base64

    srv, payer, t1, _t2, sig1 = block_server
    got = rpc_call(srv.addr, "getTransaction", [b58_encode(sig1)])["result"]
    assert got["slot"] == 9
    assert base64.b64decode(got["transaction"][0]) == t1
    # unknown signature -> null
    assert rpc_call(srv.addr, "getTransaction",
                    [b58_encode(b"Z" * 64)])["result"] is None
    sigs = rpc_call(srv.addr, "getSignaturesForAddress",
                    [b58_encode(payer)])["result"]
    assert len(sigs) == 2  # both transfers touch the payer
    assert sigs[0]["slot"] == 9
    lim = rpc_call(srv.addr, "getSignaturesForAddress",
                   [b58_encode(payer), {"limit": 1}])["result"]
    assert len(lim) == 1


class _WsClient:
    """Minimal RFC 6455 client for tests (client frames MASKED)."""

    def __init__(self, addr):
        import base64
        import socket

        self.sock = socket.create_connection(addr, timeout=10)
        key = base64.b64encode(b"0123456789abcdef").decode()
        self.sock.sendall(
            (f"GET / HTTP/1.1\r\nhost: x\r\nupgrade: websocket\r\n"
             f"connection: Upgrade\r\nsec-websocket-key: {key}\r\n"
             f"sec-websocket-version: 13\r\n\r\n").encode())
        head = b""
        while b"\r\n\r\n" not in head:
            head += self.sock.recv(4096)
        assert b"101" in head.split(b"\r\n", 1)[0]
        self._buf = head.split(b"\r\n\r\n", 1)[1]

    def send(self, obj):
        import json as _json
        import os as _os
        import struct

        payload = _json.dumps(obj).encode()
        mask = _os.urandom(4)
        n = len(payload)
        head = bytes([0x81])
        if n < 126:
            head += bytes([0x80 | n])
        else:
            head += bytes([0x80 | 126]) + struct.pack(">H", n)
        body = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        self.sock.sendall(head + mask + body)

    def recv(self):
        import json as _json

        from firedancer_tpu.protocol.websocket import decode_frame

        while True:
            # server frames are unmasked: parse directly
            if len(self._buf) >= 2:
                n = self._buf[1] & 0x7F
                off = 2
                if n == 126:
                    n = int.from_bytes(self._buf[2:4], "big")
                    off = 4
                if len(self._buf) >= off + n:
                    payload = self._buf[off : off + n]
                    self._buf = self._buf[off + n :]
                    return _json.loads(payload)
            chunk = self.sock.recv(65536)
            if not chunk:
                return None
            self._buf += chunk

    def close(self):
        self.sock.close()


def test_ws_slot_and_signature_subscriptions(block_server):
    import time

    srv, _payer, _t1, _t2, sig1 = block_server
    c = _WsClient(srv.addr)
    try:
        c.send({"jsonrpc": "2.0", "id": 1, "method": "slotSubscribe"})
        sub = c.recv()
        assert isinstance(sub["result"], int)
        c.send({"jsonrpc": "2.0", "id": 2, "method": "signatureSubscribe",
                "params": [b58_encode(sig1)]})
        sub2 = c.recv()
        assert isinstance(sub2["result"], int)
        # ordinary request/response also works over the socket
        c.send({"jsonrpc": "2.0", "id": 3, "method": "getSlot"})
        assert c.recv()["result"] == 42
        # push notifications arrive
        for _ in range(20):
            if srv._subs:
                break
            time.sleep(0.05)
        srv.notify_slot(43, parent=42, root=40)
        note = c.recv()
        assert note["method"] == "slotNotification"
        assert note["params"]["result"]["slot"] == 43
        srv.notify_signature(sig1, 43)
        note2 = c.recv()
        assert note2["method"] == "signatureNotification"
        assert note2["params"]["result"]["context"]["slot"] == 43
        # unsubscribe works
        c.send({"jsonrpc": "2.0", "id": 4, "method": "slotUnsubscribe",
                "params": [sub["result"]]})
        assert c.recv()["result"] is True
    finally:
        c.close()


def test_ws_account_subscription(block_server):
    from firedancer_tpu.flamenco.runtime import acct_build
    from firedancer_tpu.funk import Funk

    srv, payer, *_ = block_server
    funk = Funk()
    funk.rec_insert(None, payer, acct_build(909))
    srv.view.funk = funk
    c = _WsClient(srv.addr)
    try:
        c.send({"jsonrpc": "2.0", "id": 1, "method": "accountSubscribe",
                "params": [b58_encode(payer)]})
        assert isinstance(c.recv()["result"], int)
        srv.notify_account(payer)
        note = c.recv()
        assert note["method"] == "accountNotification"
        assert note["params"]["result"]["value"]["lamports"] == 909
    finally:
        c.close()


def test_get_program_accounts_and_inflation(server):
    from firedancer_tpu.flamenco import bpf_loader as bl
    from firedancer_tpu.flamenco.runtime import acct_build

    srv, pub = server
    owner = bl.UPGRADEABLE_LOADER_PROGRAM
    k1 = hashlib.sha256(b"gpa1").digest()
    k2 = hashlib.sha256(b"gpa2").digest()
    srv.view.funk.rec_insert(None, k1, acct_build(5, data=b"x", owner=owner))
    srv.view.funk.rec_insert(None, k2, acct_build(6, data=b"y", owner=owner))
    got = rpc_call(srv.addr, "getProgramAccounts",
                   [b58_encode(owner)])["result"]
    assert {a["pubkey"] for a in got} == {b58_encode(k1), b58_encode(k2)}
    assert all(a["account"]["lamports"] in (5, 6) for a in got)
    gov = rpc_call(srv.addr, "getInflationGovernor")["result"]
    assert gov["initial"] == 0.08
    rate = rpc_call(srv.addr, "getInflationRate")["result"]
    assert 0.015 <= rate["total"] <= 0.08
    assert abs(rate["validator"] + rate["foundation"] - rate["total"]) < 1e-9
