"""Consensus tests: LMD-GHOST fork choice and TowerBFT lockouts — the
worked examples from the reference's tower spec drive the assertions."""

import pytest

from firedancer_tpu.choreo import Ghost, Tower
from firedancer_tpu.choreo.tower import MAX_LOCKOUT, Vote


# -- ghost --------------------------------------------------------------------


def _fork_tree():
    r"""1 -- 2 -- 3 -- 4
              \-- 5"""
    g = Ghost(1)
    g.insert(2, 1)
    g.insert(3, 2)
    g.insert(4, 3)
    g.insert(5, 2)
    return g


def test_ghost_head_follows_stake():
    g = _fork_tree()
    g.vote(b"A", 4, 10)
    assert g.head() == 4
    g.vote(b"B", 5, 15)
    assert g.head() == 5
    assert g.weight(2) == 25  # both forks' stake flows through 2


def test_ghost_lmd_vote_moves():
    g = _fork_tree()
    g.vote(b"A", 4, 10)
    g.vote(b"A", 5, 10)  # latest message only: stake MOVES
    assert g.weight(4) == 0
    assert g.weight(5) == 10
    assert g.head() == 5


def test_ghost_tie_breaks_low_slot():
    g = _fork_tree()
    g.vote(b"A", 4, 10)
    g.vote(b"B", 5, 10)
    assert g.head() == 4  # equal weight: lower branch slot wins (3 < 5)


def test_ghost_publish_prunes_exact():
    g = _fork_tree()
    assert g.publish(3) == 3  # drops 1, 2, 5; keeps 3, 4
    assert set(g.nodes) == {3, 4}
    assert g.root == 3
    with pytest.raises(ValueError):
        g.insert(6, 5)  # pruned parent is gone


def test_ghost_is_ancestor():
    g = _fork_tree()
    assert g.is_ancestor(2, 4) and g.is_ancestor(2, 5)
    assert not g.is_ancestor(3, 5)
    assert g.is_ancestor(4, 4)


# -- tower: the spec's worked examples ---------------------------------------


def tower_with(votes):
    t = Tower()
    t.votes.extend(Vote(s, c) for s, c in votes)
    return t


def test_vote_expiry_example():
    """fd_tower.h: tower [1|4, 2|3, 3|2, 4|1]; vote 9 expires 4 and 3
    (expirations 6, 7) but NOT 2 (expiry is top-down contiguous)."""
    t = tower_with([(1, 4), (2, 3), (3, 2), (4, 1)])
    t.vote(9)
    assert [(v.slot, v.conf) for v in t.votes] == [(1, 4), (2, 3), (9, 1)]


def test_vote_doubling_example():
    """Continuing: vote 11 stacks on 9 and doubles only the consecutive
    confirmation counts."""
    t = tower_with([(1, 4), (2, 3), (9, 1)])
    t.vote(11)
    assert [(v.slot, v.conf) for v in t.votes] == [
        (1, 4), (2, 3), (9, 2), (11, 1),
    ]


def test_full_cascade_doubles_everything():
    t = tower_with([(1, 4), (2, 3), (3, 2), (4, 1)])
    t.vote(5)
    assert [(v.slot, v.conf) for v in t.votes] == [
        (1, 5), (2, 4), (3, 3), (4, 2), (5, 1),
    ]


def test_rooting_at_max_lockout():
    t = Tower()
    rooted = []
    for s in range(1, 40):
        r = t.vote(s)
        if r is not None:
            rooted.append((s, r))
    # a fully consecutive tower roots its bottom vote once conf hits 32
    assert rooted and rooted[0] == (32, 1)
    assert t.root is not None
    assert len(t.votes) <= MAX_LOCKOUT


def test_lockout_check_blocks_other_fork():
    g = _fork_tree()
    t = Tower()
    t.vote(3)
    t.vote(4)  # tower: [3|2, 4|1]; expirations 7, 6
    # voting for 5 (other fork) at slot 5: 4 not expired (exp 6) -> locked
    assert not t.lockout_check(5, g.is_ancestor)
    # after expiry both votes are dead for the other fork: slot 8 > 6, 7
    g.insert(8, 5)
    assert t.lockout_check(8, g.is_ancestor)


def test_threshold_check():
    t = tower_with([(s, 10 - s) for s in range(1, 10)])  # depth 9 tower
    total = 100
    # the depth-8 vote (slot 1) needs 2/3 of stake on its fork
    assert t.threshold_check(11, lambda s: 70, total)
    assert not t.threshold_check(11, lambda s: 60, total)
    shallow = tower_with([(1, 2), (2, 1)])
    assert shallow.threshold_check(3, lambda s: 0, total)  # too shallow


def test_switch_check():
    g = _fork_tree()
    t = Tower()
    t.vote(4)
    total = 100
    # same fork (descendant of 4... here 4 itself): no proof needed
    assert t.switch_check(4, g.is_ancestor, conflicting_stake=0, total_stake=total)
    # other fork: needs >= 38% conflicting stake
    assert not t.switch_check(5, g.is_ancestor, conflicting_stake=30, total_stake=total)
    assert t.switch_check(5, g.is_ancestor, conflicting_stake=40, total_stake=total)
