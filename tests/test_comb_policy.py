"""Comb-bank PROMOTION POLICY under adversarial traffic (VERDICT r4
weak #4: the promote-threshold policy had no cache-thrash test).

Pure host policy tests — no device dispatch: _signer_slots is the
decision function; comb installation is simulated the way _fill_bank
would commit it.  The property under attack: a spray of one-shot
pubkeys (cache-thrash spam) must neither evict established hot signers
nor grow state without bound, and a genuinely hot signer must still
get promoted even when its threshold crossing races a full queue."""

import hashlib

from firedancer_tpu.runtime.verify import VerifyStage


def mk(comb_slots=8, threshold=2):
    # no ins/outs: only the policy surface is exercised
    return VerifyStage("v", ins=[], outs=[], comb_slots=comb_slots,
                       promote_threshold=threshold)


def pk(tag) -> bytes:
    return hashlib.sha256(b"cp:%d" % tag).digest()


def install_queued(v):
    """Simulate _fill_bank's commit: queued pubkeys get slots."""
    for p in v._fill_queue:
        v._slot_of[p] = v._free_slots.pop(0)
    v._fill_queue.clear()


def test_hot_signers_promote_and_hit():
    v = mk()
    hot = [pk(i) for i in range(4)]
    for p in hot:
        assert v._signer_slots([p]) is None  # first sighting: miss
        assert v._signer_slots([p]) is None  # second: queued, still miss
    assert set(v._fill_queue) == set(hot)
    install_queued(v)
    for p in hot:
        slots = v._signer_slots([p])
        assert slots is not None and len(slots) == 1  # cached lane


def test_one_shot_spam_does_not_promote_or_grow():
    v = mk(comb_slots=8, threshold=2)
    for i in range(100_000):
        assert v._signer_slots([pk(1_000_000 + i)]) is None
    # nothing promoted (every spam key seen once), queue empty,
    # counter map bounded by the spam guard
    assert not v._fill_queue
    assert not v._slot_of
    assert len(v._seen_cnt) <= 16 * 256 + 1


def test_spam_cannot_evict_established_combs():
    v = mk(comb_slots=4, threshold=2)
    hot = [pk(i) for i in range(4)]
    for p in hot:
        v._signer_slots([p])
        v._signer_slots([p])
    install_queued(v)
    assert not v._free_slots  # bank full of hot signers
    # REPEATED spam (each attacker key crosses the threshold) cannot
    # claim a slot or displace anyone: no free slots remain
    for i in range(10_000):
        a = pk(2_000_000 + i % 50)
        v._signer_slots([a])
        v._signer_slots([a])
    assert not v._fill_queue or all(p not in v._slot_of
                                    for p in v._fill_queue)
    for p in hot:
        assert p in v._slot_of  # established combs untouched
        assert v._signer_slots([p]) is not None


def test_threshold_crossing_racing_full_queue_still_promotes():
    """The >= (not ==) rule: a hot signer whose crossing coincided with
    a full fill queue must promote on a LATER sighting."""
    v = mk(comb_slots=2, threshold=2)
    blockers = [pk(10), pk(11)]
    for p in blockers:
        v._signer_slots([p])
        v._signer_slots([p])
    assert len(v._fill_queue) == 2  # queue at capacity (== comb_slots)
    late = pk(12)
    v._signer_slots([late])
    v._signer_slots([late])  # crossing races the full queue: NOT queued
    assert late not in v._fill_queue
    install_queued(v)  # blockers take both slots; queue drains
    v2 = mk(comb_slots=4, threshold=2)  # same policy, roomier bank
    # direct continuation on v: no free slots left, so late still can't
    # promote (correct — the bank is full); with capacity the rule fires
    for p in (pk(20), pk(21)):
        v2._signer_slots([p])
        v2._signer_slots([p])
    # fill queue at 2 < comb_slots=4: a third hot signer queues fine
    v2._signer_slots([late])
    v2._signer_slots([late])
    assert late in v2._fill_queue


def test_seen_counter_flush_spares_promoted_signers():
    v = mk(comb_slots=2, threshold=2)
    hot = pk(30)
    v._signer_slots([hot])
    v._signer_slots([hot])
    install_queued(v)
    # spam enough one-shot keys to trip the counter flush
    for i in range(16 * 256 + 10):
        v._signer_slots([pk(3_000_000 + i)])
    assert hot in v._slot_of  # promotion survives the flush
    assert v._signer_slots([hot]) is not None


def test_mixed_signers_fall_back_to_generic_lane():
    """A txn with one cached and one uncached signer rides the generic
    kernel (the cached lane requires ALL signers cached)."""
    v = mk(comb_slots=4, threshold=1)
    a = pk(40)
    v._signer_slots([a])
    install_queued(v)
    assert v._signer_slots([a]) is not None
    assert v._signer_slots([a, pk(41)]) is None
