"""External ed25519 conformance vectors (SURVEY §4.4, VERDICT r2 task 5).

Two public fixture sets, read as DATA from the reference tree at test time:

  - Project Wycheproof ed25519 verify vectors (public Apache-2.0 test data,
    embedded in the reference as a generated C table,
    src/ballet/ed25519/test_ed25519_wycheproof.c) — 100+ cases covering
    malformed signatures, non-canonical S, wrong-order points, truncations.
    The reference requires verify(...) == ok EXACTLY (test_ed25519.c:1082);
    so do we, for both the python ref and the TPU kernel.
  - The Zcash-derived signature-malleability fixtures
    (test_ed25519_signature_malleability_should_{pass,fail}.bin): 96-byte
    (sig || pub) records over the fixed message "Zcash", exercising every
    combination of small-order A/R and non-canonical encodings.

Breaking on either set means a strictness divergence from the reference's
accept set — exactly the silent-shared-misunderstanding failure mode
self-referential testing can't catch.
"""

import os
import re

import numpy as np
import pytest


from firedancer_tpu.ops.ref import ed25519_ref as ref

REF_DIR = "/root/reference/src/ballet/ed25519"
WYCHEPROOF_C = os.path.join(REF_DIR, "test_ed25519_wycheproof.c")
MALLEABILITY = {
    True: os.path.join(REF_DIR, "test_ed25519_signature_malleability_should_pass.bin"),
    False: os.path.join(REF_DIR, "test_ed25519_signature_malleability_should_fail.bin"),
}

pytestmark = pytest.mark.skipif(
    not os.path.exists(WYCHEPROOF_C),
    reason="reference fixture tree not mounted",
)


def _c_bytes(lit: str) -> bytes:
    """Decode a C string literal body ("\\x41\\x42...") to bytes."""
    return lit.encode("latin1").decode("unicode_escape").encode("latin1")


def load_wycheproof():
    src = open(WYCHEPROOF_C, encoding="latin1").read()
    pat = re.compile(
        r"\.tc_id\s*=\s*(\d+),\s*"
        r"\.comment\s*=\s*\"((?:[^\"\\]|\\.)*)\",\s*"
        r"\.msg\s*=\s*\(uchar const \*\)\"((?:[^\"\\]|\\.)*)\",\s*"
        r"\.msg_sz\s*=\s*(\d+)UL,\s*"
        r"\.sig\s*=\s*\"((?:[^\"\\]|\\.)*)\",\s*"
        r"\.pub\s*=\s*\"((?:[^\"\\]|\\.)*)\",\s*"
        r"\.ok\s*=\s*(\d+)",
        re.S,
    )
    out = []
    for m in pat.finditer(src):
        tc_id, comment, msg, msg_sz, sig, pub, ok = m.groups()
        msg_b = _c_bytes(msg)
        sig_b = _c_bytes(sig)
        pub_b = _c_bytes(pub)
        assert len(msg_b) == int(msg_sz), f"tc {tc_id}: msg decode length"
        # C literals NUL-pad short arrays (e.g. sig given as < 64 chars)
        sig_b = sig_b[:64].ljust(64, b"\x00")
        pub_b = pub_b[:32].ljust(32, b"\x00")
        out.append((int(tc_id), msg_b, sig_b, pub_b, bool(int(ok))))
    assert len(out) > 100, f"only parsed {len(out)} wycheproof vectors"
    return out


def load_malleability(should_pass: bool):
    raw = open(MALLEABILITY[should_pass], "rb").read()
    assert len(raw) % 96 == 0
    return [
        (raw[o : o + 64], raw[o + 64 : o + 96]) for o in range(0, len(raw), 96)
    ]


# -- python reference implementation ------------------------------------------


def test_wycheproof_python_ref():
    bad = []
    for tc_id, msg, sig, pub, ok in load_wycheproof():
        if ref.verify(msg, sig, pub) != ok:
            bad.append(tc_id)
    assert not bad, f"python ref diverges from Wycheproof on tc_ids {bad}"


@pytest.mark.parametrize("should_pass", [True, False])
def test_malleability_python_ref(should_pass):
    msg = b"Zcash"
    bad = [
        i
        for i, (sig, pub) in enumerate(load_malleability(should_pass))
        if ref.verify(msg, sig, pub) != should_pass
    ]
    assert not bad, (
        f"python ref diverges from malleability should_"
        f"{'pass' if should_pass else 'fail'} at indices {bad[:10]}"
        f" ({len(bad)} total)"
    )


# -- TPU kernel ---------------------------------------------------------------


def _kernel_verdicts(cases, max_msg_len=64):
    """Run (msg, sig, pub) triples through ed25519_verify_batch, one batch."""
    import jax.numpy as jnp

    from firedancer_tpu.ops import sigverify as sv

    b = len(cases)
    msg = np.zeros((max_msg_len, b), dtype=np.int32)
    ln = np.zeros((b,), dtype=np.int32)
    sig = np.zeros((64, b), dtype=np.int32)
    pk = np.zeros((32, b), dtype=np.int32)
    for i, (m, s, p) in enumerate(cases):
        msg[: len(m), i] = np.frombuffer(m, dtype=np.uint8)
        ln[i] = len(m)
        sig[:, i] = np.frombuffer(s, dtype=np.uint8)
        pk[:, i] = np.frombuffer(p, dtype=np.uint8)
    out = sv.ed25519_verify_batch(
        jnp.asarray(msg), jnp.asarray(ln), jnp.asarray(sig), jnp.asarray(pk),
        max_msg_len=max_msg_len,
    )
    return np.asarray(out).astype(bool)


@pytest.mark.slow  # fresh sigverify compile (see conftest)
def test_wycheproof_tpu_kernel():
    vecs = [v for v in load_wycheproof() if len(v[1]) <= 64]
    verdicts = _kernel_verdicts([(m, s, p) for _, m, s, p, _ in vecs])
    bad = [
        tc_id
        for (tc_id, _, _, _, ok), got in zip(vecs, verdicts)
        if bool(got) != ok
    ]
    assert not bad, f"TPU kernel diverges from Wycheproof on tc_ids {bad}"


@pytest.mark.slow  # fresh sigverify compile (see conftest)
def test_malleability_tpu_kernel():
    msg = b"Zcash"
    cases = []
    expected = []
    for should_pass in (True, False):
        for sig, pub in load_malleability(should_pass):
            cases.append((msg, sig, pub))
            expected.append(should_pass)
    verdicts = _kernel_verdicts(cases)
    bad = [
        i for i, (got, want) in enumerate(zip(verdicts, expected))
        if bool(got) != want
    ]
    assert not bad, (
        f"TPU kernel diverges from malleability fixtures at {bad[:10]} "
        f"({len(bad)} of {len(cases)})"
    )
