"""fdlint (firedancer_tpu/analysis) tests: the topology checker's
negative cases per rule ID, the AST rules on synthetic sources, inline +
baseline suppression mechanics, launch()'s fail-fast integration, and —
the tier-1 gate itself — the analyzer running clean over the whole
shipped package via scripts/fdlint.sh.

Also regression-locks the violations fdlint found and this codebase
FIXED rather than baselined:
  - runtime/stage.py seeded its housekeeping RNG with builtin hash(name)
    (process-salted: every spawned child and every run drew a different
    phase) — FD204, now zlib.crc32;
  - runtime/verify.py and runtime/pack_stage.py stamped batch deadlines
    with time.monotonic() INSIDE after_frag (a per-frag syscall on the
    hot path) — FD202, stamping moved to before_credit (the hook
    run_once calls unconditionally; after_credit is skipped under
    backpressure).
"""

import os
import subprocess
import sys

import pytest

from firedancer_tpu.analysis import ast_rules, check_topology
from firedancer_tpu.analysis import baseline as bl
from firedancer_tpu.analysis import cli as fdcli
from firedancer_tpu.analysis.framework import all_rules, get_rule
from firedancer_tpu.analysis.topo_check import TopologyError
from firedancer_tpu.runtime import topo as ft

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "firedancer_tpu")


def _builder(links, cnc):  # a picklable module-level builder for specs
    raise AssertionError("never called: topologies here are checked, not run")


def _ids(findings):
    return sorted({f.rule for f in findings})


# -- rule registry -----------------------------------------------------------


def test_rule_registry_has_both_halves():
    rules = all_rules()
    ids = [r.id for r in rules]
    assert len(ids) == len(set(ids))
    assert len(ids) >= 8  # the acceptance floor, comfortably beaten
    assert any(i.startswith("FD1") for i in ids)  # topology half
    assert any(i.startswith("FD2") for i in ids)  # AST half
    for r in rules:
        assert r.severity in ("error", "warning") and r.summary


def test_cli_list_rules_prints_every_id(capsys):
    assert fdcli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for r in all_rules():
        assert r.id in out


# -- topology checker: negative cases per rule ID ---------------------------


def _wired_pair(depth=64, **link_kw):
    """gen -> l0 -> sink, fully declared and clean."""
    topo = ft.Topology()
    topo.link("l0", depth=depth, mtu=256, **link_kw)
    topo.stage("gen", _builder, outs=["l0"])
    topo.stage("sink", _builder, ins=["l0"])
    return topo


def test_clean_wired_topology_has_no_findings():
    assert check_topology(_wired_pair()) == []


def test_fd101_duplicate_producer():
    topo = _wired_pair()
    topo.stage("gen2", _builder, outs=["l0"])
    assert "FD101" in _ids(check_topology(topo))


def test_fd102_orphan_consumer():
    topo = ft.Topology()
    topo.link("l0", depth=64, mtu=256)
    topo.stage("sink", _builder, ins=["l0"])  # nobody produces l0
    assert "FD102" in _ids(check_topology(topo))


def test_fd103_unconsumed_link():
    topo = ft.Topology()
    topo.link("l0", depth=64, mtu=256)
    topo.stage("gen", _builder, outs=["l0"])  # nobody consumes l0
    assert "FD103" in _ids(check_topology(topo))


def test_fd104_non_pow2_depth():
    topo = _wired_pair(depth=1000)
    f = [x for x in check_topology(topo) if x.rule == "FD104"]
    assert f and "1000" in f[0].msg


def test_fd105_dcache_too_small():
    topo = _wired_pair(dcache_sz=64)  # far below footprint(256, 64)
    f = [x for x in check_topology(topo) if x.rule == "FD105"]
    assert f and "footprint" in f[0].msg
    # and the shm layer independently refuses to build it
    from firedancer_tpu.tango import shm

    with pytest.raises(ValueError):
        shm.ShmLink.create("fdtpu_test_fd105", depth=64, mtu=256,
                           dcache_sz=64)


def test_fd105_oversized_dcache_is_fine_and_real():
    """Oversizing is legal config, survives the header round-trip, and
    the checker stays quiet."""
    from firedancer_tpu.tango import shm
    from firedancer_tpu.tango.rings import DCache

    big = 2 * DCache.footprint(256, 64)
    assert check_topology(_wired_pair(dcache_sz=big)) == []
    link = shm.ShmLink.create("fdtpu_test_fd105b", depth=64, mtu=256,
                              dcache_sz=big)
    try:
        joined = shm.ShmLink.join("fdtpu_test_fd105b")
        assert joined.dcache_sz == big
        assert len(joined.dcache.data) == big
        joined.close()
    finally:
        link.close()
        link.unlink()


def test_fd106_fseq_underprovision():
    topo = ft.Topology()
    topo.link("l0", depth=64, mtu=256, n_consumers=1)
    topo.stage("gen", _builder, outs=["l0"])
    topo.stage("sink_a", _builder, ins=["l0"])
    topo.stage("sink_b", _builder, ins=["l0"])
    assert "FD106" in _ids(check_topology(topo))


def test_fd107_credit_gated_cycle():
    topo = ft.Topology()
    topo.link("ab", depth=64, mtu=256)
    topo.link("ba", depth=64, mtu=256)
    topo.stage("a", _builder, ins=["ba"], outs=["ab"], credit_gated=True)
    topo.stage("b", _builder, ins=["ab"], outs=["ba"], credit_gated=True)
    f = [x for x in check_topology(topo) if x.rule == "FD107"]
    assert f
    assert "a -> b" in f[0].msg or "b -> a" in f[0].msg


def test_fd107_silent_when_one_stage_drains():
    """The leader pipeline's pack<->bank loop shape: one non-gated stage
    on the cycle keeps draining and no deadlock is possible."""
    topo = ft.Topology()
    topo.link("ab", depth=64, mtu=256)
    topo.link("ba", depth=64, mtu=256)
    topo.stage("a", _builder, ins=["ba"], outs=["ab"])  # not gated
    topo.stage("b", _builder, ins=["ab"], outs=["ba"], credit_gated=True)
    assert "FD107" not in _ids(check_topology(topo))


def test_fd108_duplicate_names():
    topo = _wired_pair()
    topo.link("l0", depth=64, mtu=256)
    topo.stage("gen", _builder, outs=["l0"])
    ids = _ids(check_topology(topo))
    assert "FD108" in ids


def test_fd109_unknown_link():
    topo = ft.Topology()
    topo.stage("gen", _builder, outs=["ghost"])
    assert "FD109" in _ids(check_topology(topo))


def test_fd110_unpicklable_builder():
    topo = ft.Topology()
    topo.link("l0", depth=64, mtu=256)
    topo.stage("gen", lambda links, cnc: None, outs=["l0"])
    topo.stage("sink", _builder, ins=["l0"])
    assert "FD110" in _ids(check_topology(topo))


def test_fd111_isolated_stage_warns_only():
    topo = _wired_pair()
    topo.stage("loner", _builder, ins=[], outs=[])
    findings = check_topology(topo)
    assert "FD111" in _ids(findings)
    topo.validate()  # warnings never raise


def test_hand_wired_topologies_skip_graph_rules():
    """Stages with no declared wiring (pre-existing tests) stay valid."""
    topo = ft.Topology()
    topo.link("l0", depth=64, mtu=256)
    topo.stage("gen", _builder)
    topo.stage("sink", _builder)
    assert check_topology(topo) == []


def test_launch_fails_fast_in_parent_before_any_shm():
    """Satellite: a mis-wired topology raises a readable TopologyError
    from launch() itself — no child process, no shm segment."""
    topo = _wired_pair(depth=1000)  # FD104
    topo.stage("ghost_rider", _builder, ins=["ghost"])  # FD109 + FD102
    with pytest.raises(TopologyError) as ei:
        ft.launch(topo)
    msg = str(ei.value)
    assert "FD104" in msg and "FD109" in msg
    assert "pre-boot validation" in msg


def test_flagship_leader_topology_is_clean():
    from firedancer_tpu.models.leader_topo import build_leader_topology

    assert check_topology(build_leader_topology()) == []


# -- AST rules ---------------------------------------------------------------


_FRAG_SRC = '''
import time, random

class MyStage:
    def after_frag(self, in_idx, meta, payload):
        v = self.result.item()             # FD201
        a = np.asarray(self.mask)          # FD201
        jax.device_get(a)                  # FD201
        self.mask.block_until_ready()      # FD201
        x = float(payload[0])              # FD201 (non-constant arg)
        y = float("inf")                   # ok: constant
        t = time.monotonic()               # FD202
        r = random.randrange(8)            # FD203
        h = hash(payload)                  # FD204

    def during_housekeeping(self):
        import numpy as np
        return np.asarray(self.mask)       # ok: housekeeping is blessed
'''


def test_frag_rules_fire_and_scope_to_frag_bodies():
    findings = ast_rules.lint_source(_FRAG_SRC, "synth.py")
    ids = [f.rule for f in findings]
    assert ids.count("FD201") == 5
    assert "FD202" in ids and "FD203" in ids and "FD204" in ids
    # the housekeeping np.asarray produced nothing
    hk_line = _FRAG_SRC[:_FRAG_SRC.index("during_housekeeping")].count("\n") + 1
    assert all(f.line < hk_line for f in findings if f.rule == "FD201")


def test_frag_rules_see_through_import_aliases():
    """`from time import monotonic` / `import numpy as xp` must not
    evade the module-call rules the PR's own fixes rely on."""
    src = '''
from time import monotonic as mono
from random import randrange
import numpy as xp

class S:
    def after_frag(self, i, m, p):
        t = mono()
        a = xp.asarray(p)
        r = randrange(4)
'''
    ids = sorted(f.rule for f in ast_rules.lint_source(src, "synth.py"))
    assert ids == ["FD201", "FD202", "FD203"]


def test_fd205_ignores_defs_in_nested_class_scopes():
    """A method of a nested class does not shadow the module-level
    builder the Name resolves to — no false positive."""
    src = '''
def wire(topo):
    class Helper:
        def build_x(self):
            return None
    topo.stage("a", build_x)
'''
    assert ast_rules.lint_source(src, "synth.py") == []


def test_fd105_unaligned_dcache_sz():
    from firedancer_tpu.tango import shm
    from firedancer_tpu.tango.rings import DCache

    odd = DCache.footprint(256, 64) + 8  # big enough, but not 64-aligned
    topo = _wired_pair(dcache_sz=odd)
    f = [x for x in check_topology(topo) if x.rule == "FD105"]
    assert f and "granule" in f[0].msg
    with pytest.raises(ValueError):
        shm.ShmLink.create("fdtpu_test_fd105c", depth=64, mtu=256,
                           dcache_sz=odd)


def test_fd205_lambda_and_nested_builders():
    src = '''
def wire(topo):
    def local_builder(links, cnc):
        return None
    topo.stage("a", lambda links, cnc: None)
    topo.stage("b", local_builder)
    topo.stage("c", module_builder)
'''
    findings = ast_rules.lint_source(src, "synth.py")
    assert [f.rule for f in findings] == ["FD205", "FD205"]


def test_fd206_bare_except_unless_reraised():
    src = '''
try:
    x = 1
except:
    pass
try:
    y = 2
except:
    raise
'''
    findings = ast_rules.lint_source(src, "synth.py")
    assert [f.rule for f in findings] == ["FD206"]
    assert findings[0].line == 4


def test_fd200_unparseable_file():
    findings = ast_rules.lint_source("def broken(:\n", "synth.py")
    assert [f.rule for f in findings] == ["FD200"]


def test_fd209_unseeded_randomness_scoped_to_chaos():
    """ISSUE 7 satellite: every entropy source inside chaos/ must thread
    the run seed through utils/rng — os.urandom, secrets.*, uuid4, and
    unseeded generator constructions are flagged there, and ONLY there
    (net.py et al legitimately use os.urandom for protocol CIDs)."""
    src = '''
import os
import secrets
import random
import uuid
import numpy as np

cid = os.urandom(8)
tok = secrets.token_bytes(16)
pick = secrets.choice(options)
uid = uuid.uuid4()
r1 = random.Random()
r2 = np.random.default_rng()
'''
    findings = ast_rules.lint_source(
        src, "firedancer_tpu/chaos/population.py")
    assert [f.rule for f in findings] == ["FD209"] * 6
    # seeded constructions pass — including METHODS on seeded instances
    # (the rule's own prescribed fix must not trip the rule)
    ok = '''
import random
import numpy as np
from firedancer_tpu.utils.rng import Rng

rng = Rng(seed, 7)
r1 = random.Random(seed)
bits = r1.getrandbits(64)
pick = r1.choice(options)
r2 = np.random.default_rng(seed)
'''
    assert ast_rules.lint_source(
        ok, "firedancer_tpu/chaos/scenario.py") == []
    # identical entropy OUTSIDE chaos/ is not FD209's business
    assert ast_rules.lint_source(src, "firedancer_tpu/runtime/net.py") == []
    # the process-global random module in chaos/ is FD203's catch (the
    # division of labor _check_chaos_entropy documents): still an error
    glob = "import random\npick = random.choice([1, 2])\n"
    assert [f.rule for f in ast_rules.lint_source(
        glob, "firedancer_tpu/chaos/scenario.py")] == ["FD203"]


def test_fd209_listed_and_chaos_package_clean():
    from firedancer_tpu.analysis.framework import all_rules

    assert "FD209" in {r.id for r in all_rules()}
    findings = ast_rules.lint_path(os.path.join(PKG, "chaos"))
    assert [f for f in findings if f.rule == "FD209"] == []


def test_inline_disable_suppresses_named_rule_only():
    src = ("class S:\n"
           "    def after_frag(self, i, m, p):\n"
           "        t = time.time()  "
           "# fdlint: disable=FD202 -- latency probe\n"
           "        h = hash(p)\n")
    findings = ast_rules.lint_source(src, "synth.py")
    by_rule = {f.rule: f for f in findings}
    assert by_rule["FD202"].suppressed == "inline"
    assert by_rule["FD204"].suppressed is None


def test_baseline_grandfathers_exact_counts(tmp_path):
    base = tmp_path / "baseline.toml"
    base.write_text(
        '[[suppress]]\npath = "synth.py"\nrule = "FD204"\ncount = 1\n'
        'reason = "test"\n'
    )
    src = "a = hash(b)\nc = hash(d)\n"
    findings = ast_rules.lint_source(src, "synth.py")
    bl.apply_baseline(findings, bl.load_baseline(str(base)))
    assert [f.suppressed for f in findings] == ["baseline", None]


def test_write_baseline_roundtrip(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text("a = hash(b)\n")
    base = tmp_path / "generated.toml"
    rc = fdcli.main(["--write-baseline", "--no-topo",
                     "--baseline", str(base), str(src)])
    assert rc == 0
    # with the generated baseline the same tree is clean
    assert fdcli.main(["--no-topo", "--baseline", str(base),
                       str(src)]) == 0
    # without it, the finding fails the run
    assert fdcli.main(["--no-topo", "--no-baseline", str(src)]) == 1


def test_prune_baseline_drops_and_shrinks_stale_entries(tmp_path):
    """Satellite (ISSUE 15): baseline hygiene.  Entries whose
    file/rule no longer produces a finding are dropped; overcounted
    entries shrink to the live count; live entries keep their reason
    verbatim; entries OUTSIDE the run's analyzed scope pass through
    untouched (a scoped run must not eat suppressions it never
    looked at)."""
    src = tmp_path / "mod.py"
    src.write_text("a = hash(b)\n")  # exactly ONE live FD204
    base = tmp_path / "baseline.toml"
    base.write_text(
        # stale: rule fixed long ago, no current finding
        '[[suppress]]\npath = "%s"\nrule = "FD203"\ncount = 2\n'
        'reason = "fixed since"\n'
        # overcounted: 3 grandfathered, 1 live
        '[[suppress]]\npath = "%s"\nrule = "FD204"\ncount = 3\n'
        'reason = "keep me"\n'
        # stale: the file itself was deleted (still inside the scope)
        '[[suppress]]\npath = "%s"\nrule = "FD204"\ncount = 1\n'
        'reason = "file deleted"\n'
        # outside the scanned tree entirely: must survive verbatim
        '[[suppress]]\npath = "elsewhere/keep.py"\nrule = "FD202"\n'
        'count = 5\nreason = "not my scope"\n'
        % (src, src, tmp_path / "gone.py")
    )
    rc = fdcli.main(["--prune-baseline", "--no-topo", "--no-abi",
                     "--baseline", str(base), str(tmp_path)])
    assert rc == 0
    entries = bl.load_entries(str(base))
    assert [(e["rule"], int(e["count"])) for e in entries] == \
        [("FD204", 1), ("FD202", 5)]
    assert entries[0]["reason"] == "keep me"  # shrunk from 3, reason kept
    assert entries[1]["reason"] == "not my scope"  # out of scope: verbatim
    # the pruned file still suppresses exactly the live finding
    assert fdcli.main(["--no-topo", "--no-abi", "--baseline", str(base),
                       str(tmp_path)]) == 0


def test_prune_baseline_scoped_abi_run_keeps_lint_entries(tmp_path):
    """Regression: `--abi --prune-baseline` analyzes zero lint paths —
    it must NOT drop the shipped verify.py FD214 suppressions as
    'stale' just because this run never linted them."""
    import shutil

    base = tmp_path / "baseline.toml"
    shutil.copy(bl.DEFAULT_BASELINE, base)
    rc = fdcli.main(["--abi", "--prune-baseline", "--baseline",
                     str(base)])
    assert rc == 0
    assert bl.load_baseline(str(base)) == {
        ("firedancer_tpu/runtime/verify.py", "FD214"): 2,
    }


def test_prune_baseline_keeps_shipped_file_intact(tmp_path):
    """Pruning the SHIPPED baseline against the shipped tree is a
    no-op: its only entry (verify.py FD214 x2) is live, so nothing is
    stale — the hygiene pass never eats a justified suppression."""
    import shutil

    base = tmp_path / "baseline.toml"
    shutil.copy(bl.DEFAULT_BASELINE, base)
    rc = fdcli.main(["--prune-baseline", "--no-topo", "--no-abi",
                     "--baseline", str(base),
                     os.path.join(PKG, "runtime", "verify.py")])
    assert rc == 0
    assert bl.load_baseline(str(base)) == {
        ("firedancer_tpu/runtime/verify.py", "FD214"): 2,
    }


def test_abi_pass_is_clean_and_wired_into_the_cli():
    """Satellite (ISSUE 15): `--abi` alone exits 0 over the shipped
    repo (zero cross-language drift after the binding fixes), and the
    FD3xx family is registered alongside FD1xx/FD2xx."""
    assert fdcli.main(["--abi"]) == 0
    ids = {r.id for r in all_rules()}
    assert {"FD301", "FD302", "FD303", "FD304", "FD305", "FD306",
            "FD307", "FD308"} <= ids


# -- the tier-1 gate + fixed-violation regressions ---------------------------


def test_fdlint_script_runs_clean_over_shipped_tree():
    """Satellite: scripts/fdlint.sh = compileall + analyzer, exit 0.
    This is the CI hook — any new violation in the package fails here."""
    r = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "fdlint.sh")],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, f"fdlint gate failed:\n{r.stdout}\n{r.stderr}"
    assert "clean" in r.stdout


def test_fixed_violations_stay_fixed():
    """The three true positives fdlint found at introduction were FIXED,
    not baselined: their files carry no unsuppressed error finding, and
    the baseline holds ONLY the documented FD214 comb-install exception
    (ISSUE 13 — see baseline.toml for the reasoning)."""
    for mod in ("runtime/stage.py", "runtime/verify.py",
                "runtime/pack_stage.py"):
        findings = [f for f in ast_rules.lint_file(os.path.join(PKG, mod))
                    if get_rule(f.rule).severity == "error"]
        bl.apply_baseline(findings, bl.load_baseline())
        live = [f for f in findings if not f.suppressed]
        assert live == [], f"{mod}: {[f.format() for f in live]}"
    assert set(bl.load_baseline()) == {
        ("firedancer_tpu/runtime/verify.py", "FD214"),
    }


def test_stage_housekeeping_phase_survives_hash_salt():
    """Regression for the FD204 fix: the housekeeping schedule derived
    from (name, seed) must be identical across interpreters with
    different hash salts — exactly what builtin hash(name) broke for
    every spawned child."""
    prog = (
        "from firedancer_tpu.runtime.stage import Stage\n"
        "s = Stage('verify0', seed=7)\n"
        "s._housekeeping()\n"
        "print(s._next_housekeeping)\n"
    )
    outs = set()
    for salt in ("0", "1"):
        env = {**os.environ, "PYTHONHASHSEED": salt, "JAX_PLATFORMS": "cpu"}
        r = subprocess.run([sys.executable, "-c", prog], env=env,
                           capture_output=True, text=True, timeout=120,
                           cwd=REPO)
        assert r.returncode == 0, r.stderr
        outs.add(r.stdout.strip())
    assert len(outs) == 1, f"schedule depends on hash salt: {outs}"


def test_verify_deadline_close_still_works():
    """The FD202 fix moved deadline stamping to before_credit (the hook
    run_once calls unconditionally every iteration, unlike after_credit
    which is skipped under backpressure); a partial batch must still
    close once the deadline passes."""
    import time as _time

    from firedancer_tpu.runtime.verify import VerifyStage

    st = VerifyStage("v", batch=8, batch_deadline_s=0.01,
                     precomputed_ok=True)
    from firedancer_tpu.runtime.benchg import gen_transfer_pool

    payload = gen_transfer_pool(1)[0]
    meta = [0] * 8
    st.after_frag(0, meta, payload)
    assert st._gen.elems and st._gen.opened_at == 0.0
    st.before_credit()  # stamps the clock (even under backpressure)
    assert st._gen.opened_at > 0.0
    _time.sleep(0.02)
    st.after_credit()  # deadline passed -> closes + dispatches
    assert not st._gen.elems
    st.flush()
    assert st.metrics.get("txn_verified") == 1


def test_partial_declaration_never_fires_absence_rules():
    """A hand-wired (undeclared) stage may be the missing producer or
    consumer: FD102/FD103 need the FULL graph declared, while
    evidence-based rules (here FD101) still fire on the subset."""
    topo = ft.Topology()
    topo.link("l0", depth=64, mtu=256)
    topo.stage("mystery", _builder)  # actually produces l0, undeclared
    topo.stage("sink", _builder, ins=["l0"])
    assert check_topology(topo) == []
    topo.validate()  # launch() accepts the mixed topology
    # ...but a duplicate producer among the declared subset still fails
    topo.stage("gen_a", _builder, outs=["l0"])
    topo.stage("gen_b", _builder, outs=["l0"])
    assert "FD101" in _ids(check_topology(topo))


# -- FD207: per-frag FFI crossings --------------------------------------------


_FFI_FRAG_SRC = '''
import ctypes
from firedancer_tpu.protocol.txn_native import txn_parse_packed
from firedancer_tpu.tango import tcache_native as tn

class MyStage:
    def after_frag(self, in_idx, meta, payload):
        d = txn_parse_packed(payload)        # FD207: from-import of *native*
        self._lib.fd_exec_batch(payload)     # FD207: _lib handle
        tn.insert(payload)                   # FD207: native-module alias
        f = ctypes.CDLL("x.so")              # FD207: raw ctypes
        self.batch.append(payload)           # ok: plain python

    def after_credit(self):
        # burst granularity: one crossing per drained batch is the
        # design (fd_exec_batch shape) — not a frag callback, no finding
        return self._lib.fd_exec_batch(b"".join(self.batch))
'''


def test_fd207_flags_per_frag_ffi_only_in_frag_bodies():
    findings = ast_rules.lint_source(_FFI_FRAG_SRC, "synth.py")
    hits = [f for f in findings if f.rule == "FD207"]
    assert len(hits) == 4
    credit_line = _FFI_FRAG_SRC[: _FFI_FRAG_SRC.index("after_credit")].count(
        "\n") + 1
    assert all(f.line < credit_line for f in hits)


# -- FD208: allocation/formatting in metric/trace hot paths -------------------


_METRIC_HOT_SRC = '''
class MyStage:
    def after_frag(self, in_idx, meta, payload):
        self.metrics.observe(f"lat_{in_idx}", 5)       # FD208: f-string label
        self.metrics.observe("lat", len({1: 2}))       # FD208: dict literal
        self.trace(EV_X, dict(n=len(payload)))         # FD208: dict() call
        self.recorder.record(EV_X, "n={}".format(3))   # FD208: str.format
        self.metrics.observe("lat", [x for x in payload][0])  # FD208: comp
        self.metrics.observe("lat", 5)                 # ok: scalar
        self.trace(EV_X, len(payload))                 # ok: scalar
        self.metrics.inc("seen")                       # ok: not observe/trace

    def during_housekeeping(self):
        # not a frag callback: formatting here is fine (cold path)
        self.trace(EV_X, sum(len(p) for p in self.batch))
'''


def test_fd208_flags_alloc_in_observe_trace_frag_paths():
    findings = ast_rules.lint_source(_METRIC_HOT_SRC, "synth.py")
    hits = [f for f in findings if f.rule == "FD208"]
    assert len(hits) == 5
    hk_line = _METRIC_HOT_SRC[: _METRIC_HOT_SRC.index(
        "during_housekeeping")].count("\n") + 1
    assert all(f.line < hk_line for f in hits)


def test_fd208_clean_on_repo_hot_paths():
    """The shipped stages' frag callbacks observe/trace with scalars
    only — the rule that gates new code must hold on the code that
    motivated it."""
    import os

    root = os.path.join(os.path.dirname(__file__), "..", "firedancer_tpu",
                        "runtime")
    findings = ast_rules.lint_path(root)
    assert [f for f in findings if f.rule == "FD208"] == []


# -- FD210: host<->device transfers in serving frag paths ---------------------


_TRANSFER_SRC = '''
import jax
from jax import device_put

class ServeishStage:
    def after_frag(self, in_idx, meta, payload):
        a = jax.device_put(payload, self.sharding)   # FD210: per-frag commit
        b = device_put(payload)                      # FD210: from-import
        self.pending.copy_to_host_async()            # FD210: transfer kick
        self.acc.append(payload)                     # ok: host accumulation

    def after_credit(self):
        # batch-close granularity: the sanctioned place for device_put
        return jax.device_put(self.batch, self.sharding)
'''


def test_fd210_flags_per_frag_transfers_in_serve_scope():
    findings = ast_rules.lint_source(
        _TRANSFER_SRC, "firedancer_tpu/runtime/somestage.py")
    hits = [f for f in findings if f.rule == "FD210"]
    assert len(hits) == 3
    ac_line = _TRANSFER_SRC[: _TRANSFER_SRC.index("after_credit")].count(
        "\n") + 1
    assert all(f.line < ac_line for f in hits)


def test_fd210_scoped_to_runtime_and_parallel():
    # the same source outside runtime//parallel/ is not FD210's business
    findings = ast_rules.lint_source(_TRANSFER_SRC, "firedancer_tpu/waltz/x.py")
    assert [f for f in findings if f.rule == "FD210"] == []
    findings = ast_rules.lint_source(
        _TRANSFER_SRC, "firedancer_tpu/parallel/serve.py")
    assert len([f for f in findings if f.rule == "FD210"]) == 3


def test_fd210_registered_and_clean_on_repo():
    assert "FD210" in {r.id for r in all_rules()}
    import os

    for pkg in ("runtime", "parallel"):
        root = os.path.join(os.path.dirname(__file__), "..",
                            "firedancer_tpu", pkg)
        findings = ast_rules.lint_path(root)
        assert [f for f in findings if f.rule == "FD210"] == []


# -- FD211: per-frag allocation/sort in pack hot paths ------------------------


_PACK_SORT_SRC = '''
import bisect

class PackishStage:
    def after_frag(self, in_idx, meta, payload):
        self.pool.sort()                          # FD211: per-frag sort
        k = sorted(self.pool)                     # FD211: per-frag sort
        bisect.insort(self.pool, payload)         # FD211: per-frag insort
        w = {a for a in self.addrs}               # FD211: comprehension
        self.burst.append((payload, 1))           # ok: append-only handoff

    def after_credit(self):
        # burst granularity: the sanctioned place for pool work
        return sorted(self.pool)
'''


def test_fd211_flags_sort_and_comprehension_in_pack_frag():
    findings = ast_rules.lint_source(
        _PACK_SORT_SRC, "firedancer_tpu/runtime/pack_stage.py")
    hits = [f for f in findings if f.rule == "FD211"]
    assert len(hits) == 4
    ac_line = _PACK_SORT_SRC[: _PACK_SORT_SRC.index("after_credit")].count(
        "\n") + 1
    assert all(f.line < ac_line for f in hits)


def test_fd211_scoped_to_pack_modules():
    # identical source outside a pack module is not FD211's business
    findings = ast_rules.lint_source(
        _PACK_SORT_SRC, "firedancer_tpu/runtime/verify.py")
    assert [f for f in findings if f.rule == "FD211"] == []
    # the pack package itself is in scope
    findings = ast_rules.lint_source(
        _PACK_SORT_SRC, "firedancer_tpu/pack/scheduler.py")
    assert len([f for f in findings if f.rule == "FD211"]) == 4


def test_fd211_registered_and_clean_on_repo():
    assert "FD211" in {r.id for r in all_rules()}
    import os

    for rel in (("pack",), ("runtime", "pack_stage.py")):
        root = os.path.join(os.path.dirname(__file__), "..",
                            "firedancer_tpu", *rel)
        findings = ast_rules.lint_path(root)
        assert [f for f in findings if f.rule == "FD211"] == []


# -- FD212: per-frag ctypes allocation churn ----------------------------------


_CTYPES_CHURN_SRC = '''
import ctypes
from ctypes import byref as br

class RingishStage:
    def after_frag(self, in_idx, meta, payload):
        out = ctypes.create_string_buffer(1232)   # FD212: buffer per frag
        self._lib.fdr_poll(br(self._ls), out)     # FD212: byref temporary
        m = (ctypes.c_uint64 * 7)()               # FD212: array per frag
        p = ctypes.cast(out, ctypes.c_void_p)     # FD212: cast temporary
        self._burst.append(payload)               # ok: append-only handoff

    def before_credit(self):
        # burst granularity: the sanctioned place for the crossing
        return self._lib.fdr_drain(self._lsp)
'''


def test_fd212_flags_ctypes_churn_in_frag():
    findings = ast_rules.lint_source(
        _CTYPES_CHURN_SRC, "firedancer_tpu/tango/somering.py")
    hits = [f for f in findings if f.rule == "FD212"]
    assert len(hits) == 4
    bc_line = _CTYPES_CHURN_SRC[: _CTYPES_CHURN_SRC.index(
        "before_credit")].count("\n") + 1
    assert all(f.line < bc_line for f in hits)


def test_fd212_needs_ctypes_import():
    # the same shapes without a ctypes import (e.g. a math `(a*b)(x)`,
    # even with a c_-prefixed name) are not FD212's business
    src = '''
class S:
    def after_frag(self, in_idx, meta, payload):
        f = (scale * gain)(payload)
        g = (c_scale * gain)(payload)
        out = create_string_buffer(64)
'''
    findings = ast_rules.lint_source(src, "firedancer_tpu/tango/x.py")
    assert [f for f in findings if f.rule == "FD212"] == []


def test_fd212_non_ctypes_mult_callee_ok():
    # `(a * b)(x)` where neither operand references ctypes must not trip
    # the array-shape check just because the FILE imports ctypes
    src = '''
import ctypes

class S:
    def after_frag(self, in_idx, meta, payload):
        f = (scale * gain)(payload)
        m = (ctypes.c_uint64 * 7)()   # this one IS the churn shape
'''
    findings = ast_rules.lint_source(src, "firedancer_tpu/tango/x.py")
    hits = [f for f in findings if f.rule == "FD212"]
    assert len(hits) == 1
    assert "array construction" in hits[0].msg


def test_fd212_cached_byref_outside_frag_ok():
    # the tango/native.py discipline: byref/buffers cached in __init__,
    # frag-adjacent code only *uses* them
    src = '''
import ctypes

class Endpoint:
    def __init__(self):
        self._out = ctypes.create_string_buffer(1232)
        self._lsp = ctypes.byref(self._ls)

    def after_frag(self, in_idx, meta, payload):
        self._burst.append((payload, int(meta[1])))
'''
    findings = ast_rules.lint_source(src, "firedancer_tpu/tango/x.py")
    assert [f for f in findings if f.rule == "FD212"] == []


def test_fd212_registered_and_clean_on_repo():
    assert "FD212" in {r.id for r in all_rules()}
    import os

    root = os.path.join(os.path.dirname(__file__), "..", "firedancer_tpu")
    findings = ast_rules.lint_path(root)
    assert [f for f in findings if f.rule == "FD212"] == []


# -- FD213: per-frag hashing/bytes assembly in the shred path -----------------


_SHRED_CHURN_SRC = '''
import hashlib
from firedancer_tpu.ops.ref.bmtree import hash_leaf_full

class ShredishStage:
    def after_frag(self, in_idx, meta, payload):
        leaf = hash_leaf_full(payload)            # FD213: merkle churn
        node = hashlib.sha256(payload).digest()   # FD213: hash per frag
        frame = b"\\x00" * 4 + payload            # FD213: literal concat
        buf = bytes(payload)                      # FD213: bytes() per frag
        joined = b"".join(self._parts)            # FD213: join concat
        self._buf += payload                      # ok: append-only extend

    def _shred_batch(self):
        # FEC-set granularity: the sanctioned place for all of it
        root = hashlib.sha256(bytes(self._buf)).digest()
        return b"".join(self._shreds)
'''


def test_fd213_flags_hash_and_concat_in_shred_frag():
    findings = ast_rules.lint_source(
        _SHRED_CHURN_SRC, "firedancer_tpu/runtime/shredder.py")
    hits = [f for f in findings if f.rule == "FD213"]
    assert len(hits) == 5
    batch_line = _SHRED_CHURN_SRC[: _SHRED_CHURN_SRC.index(
        "_shred_batch")].count("\n") + 1
    assert all(f.line < batch_line for f in hits)


def test_fd213_scoped_to_shred_path_modules():
    # the identical body in a non-shred module is not FD213's business
    findings = ast_rules.lint_source(
        _SHRED_CHURN_SRC, "firedancer_tpu/runtime/dedup.py")
    assert [f for f in findings if f.rule == "FD213"] == []


def test_fd213_batch_granularity_ok():
    # the ShredStage discipline: frag callbacks append; hashing/framing
    # happen when the batch closes (helper methods, not frag callbacks)
    src = '''
import hashlib

class ShredStage:
    def after_frag(self, in_idx, meta, payload):
        self._buf += len(payload).to_bytes(4, "little")
        self._buf += payload

    def flush(self):
        return hashlib.sha256(bytes(self._buf)).digest()
'''
    findings = ast_rules.lint_source(
        src, "firedancer_tpu/runtime/shred_stage.py")
    assert [f for f in findings if f.rule == "FD213"] == []


def test_fd213_registered_and_clean_on_repo():
    assert "FD213" in {r.id for r in all_rules()}
    import os

    for rel in ("shredder.py", "shred_stage.py", "shred_native.py",
                "store.py", "fec_resolver.py"):
        root = os.path.join(os.path.dirname(__file__), "..",
                            "firedancer_tpu", "runtime", rel)
        findings = ast_rules.lint_path(root)
        assert [f for f in findings if f.rule == "FD213"] == []


# -- FD214: device sync outside the designated reap point ---------------------


_VERIFY_SYNC_SRC = '''
import numpy as np

class VerifyStage:
    def _accumulate(self, got, payload, tsorig):
        n = int(np.asarray(self._count))          # FD214: sync in intake
        self._elems.append(got)

    def _submit(self, acc, cached):
        res = self._dispatch(acc, cached)
        res.block_until_ready()                   # FD214: sync at submit
        self._inflight.append(res)

    def during_housekeeping(self):
        v = self._probe.item()                    # FD214: sync in hk
        self._log(v)

    def _drain(self, block):
        mask = np.asarray(self._inflight[0].result)   # ok: THE reap point
        return mask

    def _result_mask(self, head):
        return np.asarray(head.result)            # ok: reap hook

    def flush(self):
        return np.asarray(self._tail)             # ok: shutdown drain

    def after_frag(self, in_idx, meta, payload):
        x = np.asarray(meta)                      # FD201 territory, not 214
        return x


class ShardedVerifyStage(VerifyStage):
    def _close_batch(self, acc=None):
        n_ok = int(np.asarray(self._pend.n_ok))   # FD214: subclass inherits
        return n_ok


class UnrelatedHelper:
    def _submit(self):
        return np.asarray(self._x)                # not a verify-stage class
'''


def test_fd214_flags_sync_outside_reap_point():
    findings = ast_rules.lint_source(
        _VERIFY_SYNC_SRC, "firedancer_tpu/runtime/verify.py")
    hits = [f for f in findings if f.rule == "FD214"]
    msgs = [f.msg for f in hits]
    assert len(hits) == 4, msgs
    assert any("_accumulate" in m for m in msgs)
    assert any("_submit" in m for m in msgs)
    assert any("during_housekeeping" in m for m in msgs)
    assert any("_close_batch" in m for m in msgs)  # subclass inherits
    # the frag callback is FD201's jurisdiction, not re-flagged as FD214
    assert not any("after_frag" in m for m in msgs)
    assert any(f.rule == "FD201" for f in findings)


def test_fd214_scoped_to_verify_path_modules():
    # the identical body elsewhere is not FD214's business
    findings = ast_rules.lint_source(
        _VERIFY_SYNC_SRC, "firedancer_tpu/runtime/bank.py")
    assert [f for f in findings if f.rule == "FD214"] == []


def test_fd214_registered_and_baselined_on_repo():
    assert "FD214" in {r.id for r in all_rules()}
    # the repo's verify path carries exactly the two baselined
    # _fill_bank hits (deliberate comb-install sync, documented in
    # baseline.toml) and nothing else
    for rel, allowed in (("runtime/verify.py", 2),
                         ("parallel/serve.py", 0),
                         ("runtime/verify_native.py", 0)):
        root = os.path.join(os.path.dirname(__file__), "..",
                            "firedancer_tpu", rel)
        findings = [f for f in ast_rules.lint_path(root)
                    if f.rule == "FD214"]
        assert len(findings) == allowed, (rel, findings)
        assert all("_fill_bank" in f.msg for f in findings)


# -- FD215: blocking waits in hot hooks (slot-clock discipline) ---------------


_BLOCKING_SRC = '''
import time
import threading
from time import sleep as zzz

class SomeStage:
    def after_frag(self, in_idx, meta, payload):
        time.sleep(0.01)                          # FD215: sleep in frag

    def before_credit(self):
        zzz(0.5)                                  # FD215: aliased sleep

    def after_credit(self):
        self._done_event.wait()                   # FD215: unbounded wait

    def during_housekeeping(self):
        self._worker.join()                       # FD215: unbounded join
        self._lock.acquire()                      # FD215: unbounded acquire

    def flush(self):
        time.sleep(0.1)                           # not a hot hook: clean

    def before_frag(self, in_idx, seq, sig):
        ok = self._done_event.wait(0.0)           # bounded: clean
        joined = ",".join(self._parts)            # str.join(arg): clean
        got = self._lock.acquire(False)           # non-blocking: clean
        return ok and got and bool(joined)


def after_credit():
    time.sleep(1.0)                               # free function: clean
'''


def test_fd215_flags_blocking_waits_in_hot_hooks():
    findings = ast_rules.lint_source(
        _BLOCKING_SRC, "firedancer_tpu/runtime/somestage.py")
    hits = [f for f in findings if f.rule == "FD215"]
    msgs = [f.msg for f in hits]
    assert len(hits) == 5, msgs
    assert sum("time.sleep" in m for m in msgs) == 2
    assert any(".wait()" in m for m in msgs)
    assert any(".join()" in m for m in msgs)
    assert any(".acquire()" in m for m in msgs)
    # hook hits name the surface so the fix is obvious
    assert any("stage-loop hook" in m for m in msgs)
    assert any("frag callback" in m for m in msgs)


def test_fd215_suppressible_inline():
    src = ("import time\n"
           "class S:\n"
           "    def after_credit(self):\n"
           "        time.sleep(0.1)  "
           "# fdlint: disable=FD215 -- test fixture pacing\n")
    findings = [f for f in ast_rules.lint_source(src, "firedancer_tpu/x.py")
                if f.rule == "FD215"]
    # suppressions are MARKED, not dropped (reports show what a disable
    # comment ate), and the repo-clean test below counts only live hits
    assert len(findings) == 1 and findings[0].suppressed == "inline"


def test_fd215_registered_and_repo_clean():
    assert "FD215" in {r.id for r in all_rules()}
    # the slot-clock plane is the only deadline authority: the repo's
    # own stage code carries ZERO blocking waits in hot hooks
    root = os.path.join(os.path.dirname(__file__), "..", "firedancer_tpu")
    findings = [f for f in ast_rules.lint_path(root)
                if f.rule == "FD215"]
    assert findings == [], findings


# -- FD216: txn re-parse in bank-path frag callbacks (zero-copy commit) -------


_REPARSE_SRC = '''
from firedancer_tpu.protocol import txn as ft
from firedancer_tpu.protocol.txn import txn_parse
import struct

class BankishStage:
    def after_frag(self, in_idx, meta, payload):
        t = ft.txn_parse(payload)                 # FD216: qualified re-parse
        desc, end = ft.txn_unpack(payload, 0)     # FD216: descriptor re-parse
        t2 = txn_parse(payload)                   # FD216: from-import alias
        psz = struct.unpack("<H", payload[-2:])   # struct.unpack: clean
        n = int.from_bytes(payload[-2:], "little")  # offset read: clean
        return t or t2 or desc or psz or n

    def _arm_native(self):
        return ft.txn_parse(b"")                  # not a frag callback: clean


def txn_parse_free(payload):
    return txn_parse(payload)                     # free function: clean
'''


def test_fd216_flags_reparse_in_bank_frag():
    findings = ast_rules.lint_source(
        _REPARSE_SRC, "firedancer_tpu/runtime/bank.py")
    hits = [f for f in findings if f.rule == "FD216"]
    msgs = [f.msg for f in hits]
    assert len(hits) == 3, msgs
    assert sum("txn_parse" in m for m in msgs) == 2
    assert sum("txn_unpack" in m for m in msgs) == 1
    # the same source OUTSIDE the bank path is not FD216's business
    clean = [f for f in ast_rules.lint_source(
        _REPARSE_SRC, "firedancer_tpu/runtime/poh_stage.py")
        if f.rule == "FD216"]
    assert clean == [], clean


def test_fd216_suppressible_inline():
    src = ("from firedancer_tpu.protocol.txn import txn_parse\n"
           "class B:\n"
           "    def after_frag(self, in_idx, meta, payload):\n"
           "        return txn_parse(payload)  "
           "# fdlint: disable=FD216 -- replay-side decode\n")
    findings = [f for f in ast_rules.lint_source(
        src, "firedancer_tpu/runtime/bank_native.py")
        if f.rule == "FD216"]
    assert len(findings) == 1 and findings[0].suppressed == "inline"


def test_fd216_registered_and_repo_clean():
    assert "FD216" in {r.id for r in all_rules()}
    # the commit path honors the verify contract: the repo's own bank
    # modules read the packed descriptor, they never re-parse the txn
    root = os.path.join(os.path.dirname(__file__), "..", "firedancer_tpu")
    findings = [f for f in ast_rules.lint_path(root)
                if f.rule == "FD216"]
    assert findings == [], findings


# -- FD217: per-datagram Python crypto in ingress with a sweep client ---------


_NET_CRYPTO_SRC = '''
from firedancer_tpu.ops.aes import AesGcm
from firedancer_tpu.waltz.quic import _hp_mask
from . import net_native


class IngressStage:
    def __init__(self):
        self._net_client = net_native.NetClient(max_conns=1, reasm_depth=1)
        self._gcm = AesGcm(b"k" * 16)

    def _on_datagram(self, data, src):
        pt = self._gcm.open(data[:12], data[12:-16], data[-16:])  # FD217
        mask = _hp_mask(b"h" * 16, data[:16])                     # FD217
        return pt or mask

    def after_credit(self):
        data, src = self.sock.recvfrom(2048)                      # FD217
        ct, tag = self._gcm.seal(b"\\x00" * 12, data)              # FD217
        return ct, tag

    def _py_datagram(self, data, src):
        # the punt lane: the same calls are FD217-clean here
        pt = self._gcm.open(data[:12], data[12:-16], data[-16:])
        mask = _hp_mask(b"h" * 16, data[:16])
        for _ in range(2):
            data, src = self.sock.recvfrom(2048)
        return pt or mask

    def report(self, path):
        with open(path) as fh:                    # builtin open: clean
            return fh.read()
'''


def test_fd217_flags_ingress_crypto_with_sweep_client():
    findings = ast_rules.lint_source(
        _NET_CRYPTO_SRC, "firedancer_tpu/runtime/net.py")
    hits = [f for f in findings if f.rule == "FD217"]
    msgs = [f.msg for f in hits]
    assert len(hits) == 4, msgs
    assert sum(".open()" in m for m in msgs) == 1
    assert sum(".seal()" in m for m in msgs) == 1
    assert sum("recvfrom" in m for m in msgs) == 1
    assert sum("_hp_mask" in m for m in msgs) == 1
    # without the sweep-client registration the SAME hot-path calls are
    # the module's legitimate Python lane — the gate must not fire
    ungated = _NET_CRYPTO_SRC.replace(
        "self._net_client = net_native.NetClient"
        "(max_conns=1, reasm_depth=1)",
        "self._net_client_off = None")
    clean = [f for f in ast_rules.lint_source(
        ungated, "firedancer_tpu/runtime/net.py") if f.rule == "FD217"]
    assert clean == [], clean
    # and outside the net modules the rule has no opinion at all
    other = [f for f in ast_rules.lint_source(
        _NET_CRYPTO_SRC, "firedancer_tpu/runtime/verify.py")
        if f.rule == "FD217"]
    assert other == [], other


def test_fd217_suppressible_inline():
    src = ("class S:\n"
           "    def __init__(self):\n"
           "        self._sweep_client = object()\n"
           "    def _on_datagram(self, data, src):\n"
           "        return self.gcm.open(data[:12], data[12:], b'')  "
           "# fdlint: disable=FD217 -- bring-up shim\n")
    findings = [f for f in ast_rules.lint_source(
        src, "firedancer_tpu/runtime/net.py") if f.rule == "FD217"]
    assert len(findings) == 1 and findings[0].suppressed == "inline"


def test_fd217_registered_and_repo_clean():
    assert "FD217" in {r.id for r in all_rules()}
    # the ingress hot path honors the lane split: the repo's own net
    # modules keep per-datagram Python crypto in the _py_* punt lane
    root = os.path.join(os.path.dirname(__file__), "..", "firedancer_tpu")
    findings = [f for f in ast_rules.lint_path(root)
                if f.rule == "FD217"]
    assert findings == [], findings


# -- FD218: per-record Python funk mutation with the native funk lane armed ---


_BANK_FUNK_SRC = '''
from firedancer_tpu.runtime import bank_native


class BankStage:
    def __init__(self, funk, xid):
        self._sweep_client = bank_native.StageClient(n_lanes=1)
        self._sweep_client.set_funk(funk, xid)
        self.funk = funk
        self.xid = xid

    def after_frag(self, sig, frag):
        recs = self.funk.txn_recs_for_write(self.xid)        # FD218
        for key, val in frag.items():
            self.funk.rec_insert(self.xid, key, val)         # FD218
        self.funk.rec_insert_batch(self.xid, frag.items())   # clean
        return recs

    def after_credit(self):
        self.funk._root_merge([(b"k", b"v")])                # FD218
        self.funk.rec_remove(self.xid, b"dead")              # FD218

    def _drain_native(self, rows):
        # cold path, not a frag callback: per-record writes are fine
        for key, val in rows:
            self.funk.rec_insert(self.xid, key, val)
        self.funk._root_merge(rows)
'''


def test_fd218_flags_per_record_funk_mutation_with_lane_armed():
    findings = ast_rules.lint_source(
        _BANK_FUNK_SRC, "firedancer_tpu/runtime/bank.py")
    hits = [f for f in findings if f.rule == "FD218"]
    msgs = [f.msg for f in hits]
    assert len(hits) == 4, msgs
    assert sum("txn_recs_for_write" in m for m in msgs) == 1
    assert sum("rec_insert'" in m for m in msgs) == 1  # not rec_insert_batch
    assert sum("_root_merge" in m for m in msgs) == 1
    assert sum("rec_remove" in m for m in msgs) == 1
    # without the set_funk arming the SAME writes are the module's
    # legitimate Python funk lane — the gate must not fire
    ungated = _BANK_FUNK_SRC.replace(
        "self._sweep_client.set_funk(funk, xid)", "self._armed = False")
    clean = [f for f in ast_rules.lint_source(
        ungated, "firedancer_tpu/runtime/bank.py") if f.rule == "FD218"]
    assert clean == [], clean
    # and outside the bank-path modules the rule has no opinion at all
    other = [f for f in ast_rules.lint_source(
        _BANK_FUNK_SRC, "firedancer_tpu/runtime/net.py")
        if f.rule == "FD218"]
    assert other == [], other


def test_fd218_suppressible_inline():
    src = ("class S:\n"
           "    def __init__(self, c):\n"
           "        c.set_funk(None, b'')\n"
           "    def after_frag(self, sig, frag):\n"
           "        return self.funk.rec_insert(None, b'k', b'v')  "
           "# fdlint: disable=FD218 -- bring-up shim\n")
    findings = [f for f in ast_rules.lint_source(
        src, "firedancer_tpu/runtime/bank.py") if f.rule == "FD218"]
    assert len(findings) == 1 and findings[0].suppressed == "inline"


def test_fd218_registered_and_repo_clean():
    assert "FD218" in {r.id for r in all_rules()}
    # the commit hot path honors the one-crossing contract: the repo's
    # own bank modules never mutate funk per record inside a frag
    root = os.path.join(os.path.dirname(__file__), "..", "firedancer_tpu")
    findings = [f for f in ast_rules.lint_path(root)
                if f.rule == "FD218"]
    assert findings == [], findings


# -- FD219: Python write on a native-owned metric with a sweep client armed ---


_NATIVE_METRIC_SRC = '''
class BankStage:
    def __init__(self, client):
        self._sweep_client = client

    def after_frag(self, sig, frag):
        self.metrics.observe("nsweep_apply_ns", 120.0)       # FD219
        self.metrics.inc("nsweep_frags", 4)                  # FD219
        self.metrics.observe("nbank_txn_lat_ns", 9.0)        # FD219
        self.metrics.observe("frag_latency_ns", 9.0)         # non-native: ok
        self.metrics.inc("frags_in")                         # non-native: ok

    def during_housekeeping(self):
        # cold paths double-count just as surely as hot ones
        self.metrics.registry.store("nsweep_crossings", 1)   # FD219
        self.recorder.record(17, 0)          # event id, not a name: ok

    def report(self, name):
        self.metrics.observe(name, 1.0)      # dynamic name: ok
'''


def test_fd219_flags_python_writes_on_native_owned_metrics():
    findings = ast_rules.lint_source(
        _NATIVE_METRIC_SRC, "firedancer_tpu/runtime/bank.py")
    hits = [f for f in findings if f.rule == "FD219"]
    msgs = [f.msg for f in hits]
    assert len(hits) == 4, msgs
    assert sum("nsweep_apply_ns" in m for m in msgs) == 1
    assert sum("nsweep_frags" in m for m in msgs) == 1
    assert sum("nbank_txn_lat_ns" in m for m in msgs) == 1
    assert sum("nsweep_crossings" in m for m in msgs) == 1
    # without the sweep-client registration the module owns its facade:
    # the SAME writes are the legitimate Python metrics lane
    ungated = _NATIVE_METRIC_SRC.replace(
        "self._sweep_client = client", "self._client_off = client")
    clean = [f for f in ast_rules.lint_source(
        ungated, "firedancer_tpu/runtime/bank.py") if f.rule == "FD219"]
    assert clean == [], clean


def test_fd219_name_set_mirrors_metrics_schema():
    # the lint mirror must track utils/metrics.native_owned_names():
    # a native metric added to the schema without extending the mirror
    # silently escapes the double-count gate (and vice versa)
    from firedancer_tpu.utils import metrics as fm

    assert ast_rules._FD219_NATIVE_OWNED == fm.native_owned_names()


def test_fd219_suppressible_inline():
    src = ("class S:\n"
           "    def __init__(self, c):\n"
           "        self._sweep_client = c\n"
           "    def after_frag(self, sig, frag):\n"
           "        self.metrics.inc('nsweep_frags')  "
           "# fdlint: disable=FD219 -- bring-up shim\n")
    findings = [f for f in ast_rules.lint_source(
        src, "firedancer_tpu/runtime/bank.py") if f.rule == "FD219"]
    assert len(findings) == 1 and findings[0].suppressed == "inline"


def test_fd219_registered_and_repo_clean():
    assert "FD219" in {r.id for r in all_rules()}
    # the repo's own sweep-client modules never write native-owned words
    # from Python (the facade skip + this rule are the same contract)
    root = os.path.join(os.path.dirname(__file__), "..", "firedancer_tpu")
    findings = [f for f in ast_rules.lint_path(root)
                if f.rule == "FD219"]
    assert findings == [], findings
