"""Multi-device mesh sharding tests (virtual 8-device CPU mesh, conftest).

Validates the multi-chip story the driver's dryrun exercises: the sigverify
kernel jit-sharded over a jax.sharding.Mesh, pass-count reduced across
shards, uneven batches padded+masked.  Mirrors the reference's N-way verify
fan-out (fd_verify.c:46) and SURVEY §5.7/§5.8.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # XLA-compile-heavy tier (see conftest)

import __graft_entry__ as ge
from firedancer_tpu.parallel import make_mesh, pad_to_multiple, sharded_verify

MAX_MSG_LEN = ge.MAX_MSG_LEN  # shapes shared with the dryrun: one compile


def _batch(n, corrupt=()):
    msg, msg_len, sig, pk = ge._example_batch(n)
    for i in corrupt:
        sig[0, i] ^= 1
    return msg, msg_len, sig, pk


def test_mesh_construction_sizes():
    import jax

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    for n in (2, 4, 8):
        mesh = make_mesh(n)
        assert mesh.devices.size == n
        assert mesh.axis_names == ("verify",)


def test_sharded_verify_8dev_all_pass():
    mesh = make_mesh(8)
    msg, msg_len, sig, pk = _batch(16)
    ok, total = sharded_verify(mesh, msg, msg_len, sig, pk, max_msg_len=MAX_MSG_LEN)
    assert ok.shape == (16,)
    assert ok.all()
    assert total == 16


def test_sharded_verify_detects_corruption_per_shard():
    # One corrupted sig in shard 0 and one in the last shard: the mask is
    # exact and the psum'd count reflects both.
    mesh = make_mesh(8)
    msg, msg_len, sig, pk = _batch(16, corrupt=(0, 15))
    ok, total = sharded_verify(mesh, msg, msg_len, sig, pk, max_msg_len=MAX_MSG_LEN)
    expect = np.ones(16, dtype=bool)
    expect[[0, 15]] = False
    assert (ok == expect).all()
    assert total == 14


def test_sharded_verify_uneven_batch_padded():
    # 13 real elements on an 8-device mesh: padded to 16, pad lanes ignored.
    mesh = make_mesh(8)
    msg, msg_len, sig, pk = _batch(16)
    msg, msg_len, sig, pk = msg[:, :13], msg_len[:13], sig[:, :13], pk[:, :13]
    ok, total = sharded_verify(mesh, msg, msg_len, sig, pk, max_msg_len=MAX_MSG_LEN)
    assert ok.shape == (13,)
    assert ok.all()
    assert total == 13


def test_sharded_verify_2dev_matches_8dev():
    mesh2 = make_mesh(2)
    msg, msg_len, sig, pk = _batch(16, corrupt=(3,))
    ok2, total2 = sharded_verify(mesh2, msg, msg_len, sig, pk, max_msg_len=MAX_MSG_LEN)
    mesh8 = make_mesh(8)
    ok8, total8 = sharded_verify(mesh8, msg, msg_len, sig, pk, max_msg_len=MAX_MSG_LEN)
    assert (ok2 == ok8).all()
    assert total2 == total8 == 15


def test_pad_to_multiple():
    assert pad_to_multiple(0, 8) == 8
    assert pad_to_multiple(1, 8) == 8
    assert pad_to_multiple(8, 8) == 8
    assert pad_to_multiple(9, 8) == 16
