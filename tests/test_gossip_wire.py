"""CRDS wire format: Protocol enum round-trips, CrdsValue signing
rules, Ping/Pong token scheme, pull chunking, unknown-tag rejection."""

import hashlib

from firedancer_tpu.flamenco import gossip_wire as gw
from firedancer_tpu.flamenco import types as T
from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.runtime import gossip as fg


def _secret(tag):
    return hashlib.sha256(tag).digest()


def _value(tag=b"n1", wallclock=5):
    a = ("v4", T.SockAddr(bytes([127, 0, 0, 1]), 8000))
    return gw.contact_info_value(
        _secret(tag), gossip=a, tvu=a, repair=a, tpu=a, wallclock=wallclock
    )


def test_crds_value_sign_verify_roundtrip():
    v = _value()
    assert v.verify()
    enc = gw.CRDS_VALUE.encode(v)
    out = gw.CRDS_VALUE.loads(enc)
    assert out.verify()
    assert out.pubkey == ref.public_key(_secret(b"n1"))
    assert out.wallclock == 5
    # flip a byte inside the signed region -> verify fails
    bad = bytearray(enc)
    bad[70] ^= 1
    assert not gw.CRDS_VALUE.loads(bytes(bad)).verify()


def test_protocol_messages_roundtrip():
    v = _value()
    for name, payload in [
        ("push_message", (b"P" * 32, [v, _value(b"n2")])),
        ("pull_response", (b"P" * 32, [v])),
        ("pull_request", (gw.CrdsFilter(), v)),
    ]:
        enc = gw.encode_message(name, payload)
        out = gw.decode_message(enc)
        assert out is not None and out[0] == name
    assert gw.decode_message(b"\x99" * 40) is None
    assert gw.decode_message(b"") is None
    # unknown CrdsData tag inside a push -> whole datagram rejected
    raw = (2).to_bytes(4, "little") + bytes(32) + (1).to_bytes(8, "little")
    raw += bytes(64) + (7).to_bytes(4, "little")  # tag 7 unknown
    assert gw.decode_message(raw) is None


def test_ping_pong_token_scheme():
    token = hashlib.sha256(b"tok").digest()
    ping = gw.ping_make(_secret(b"pinger"), token)
    assert gw.ping_verify(ping)
    pong = gw.pong_make(_secret(b"ponger"), token)
    assert gw.pong_verify(pong, token)
    assert not gw.pong_verify(pong, b"\x00" * 32)  # wrong token
    enc = gw.encode_message("ping", ping)
    name, out = gw.decode_message(enc)
    assert name == "ping" and out.token == token


def test_node_ping_pong_verifies_peer():
    a = fg.GossipNode(_secret(b"pa"))
    b = fg.GossipNode(_secret(b"pb"))
    try:
        a.ping(b.addr)
        for _ in range(3):
            b.poll()
            a.poll()
        assert b.metrics["ping_rx"] == 1
        assert a.metrics["pong_rx"] == 1
        assert b.pubkey in a.verified_peers
    finally:
        a.close()
        b.close()


def test_pull_response_chunks_under_mtu():
    serving = fg.GossipNode(_secret(b"srv"))
    try:
        # preload the table with many third-party signed records
        for i in range(20):
            serving._upsert(_value(b"peer%d" % i, wallclock=10))
        assert len(serving._signed) == 20
        puller = fg.GossipNode(_secret(b"cli"))
        try:
            puller.pull(serving.addr)
            for _ in range(5):
                serving.poll()
                puller.poll()
            # puller learned every record (+ the server itself)
            assert len(puller.table) == 21
        finally:
            puller.close()
    finally:
        serving.close()
