"""bincode codec combinators + sysvar/vote/gossip types: round-trips,
exact wire bytes, and malformed-input rejection."""

import pytest

from firedancer_tpu.flamenco import types as T


def test_int_and_bool_wire():
    assert T.U64.encode(1) == (1).to_bytes(8, "little")
    assert T.I64.encode(-2) == (-2).to_bytes(8, "little", signed=True)
    assert T.Bool.encode(True) == b"\x01"
    with pytest.raises(T.CodecError, match="bad bool"):
        T.Bool.decode(b"\x02")
    with pytest.raises(T.CodecError, match="short"):
        T.U32.decode(b"\x01")


def test_vec_option_string_roundtrip():
    v = T.Vec(T.U16)
    assert v.loads(v.encode([1, 2, 3])) == [1, 2, 3]
    assert v.encode([7]) == (1).to_bytes(8, "little") + (7).to_bytes(2, "little")
    o = T.Option(T.U64)
    assert o.loads(o.encode(None)) is None
    assert o.loads(o.encode(9)) == 9
    assert o.encode(None) == b"\x00"
    s = T.String()
    assert s.loads(s.encode("héllo")) == "héllo"
    with pytest.raises(T.CodecError, match="trailing"):
        T.U8.loads(b"\x01\x02")


def test_clock_rent_epoch_schedule():
    c = T.Clock(slot=5, epoch=1, unix_timestamp=-3)
    assert T.CLOCK.loads(T.CLOCK.encode(c)) == c
    assert len(T.CLOCK.encode(c)) == 40

    r = T.Rent()
    assert T.RENT.loads(T.RENT.encode(r)) == r
    assert len(T.RENT.encode(r)) == 17
    # the canonical mainnet rent-exempt minimum for 0-byte accounts
    assert T.rent_exempt_minimum(r, 0) == 890_880

    es = T.EpochSchedule()
    assert T.EPOCH_SCHEDULE.loads(T.EPOCH_SCHEDULE.encode(es)) == es
    assert T.epoch_of_slot(es, 432_000 * 2 + 5) == (2, 5)


def test_vote_instruction_wire():
    vote = T.Vote(slots=[10, 11], hash=b"h" * 32, timestamp=123)
    enc = T.VOTE_INSTRUCTION.encode(("vote", vote))
    assert enc[:4] == (2).to_bytes(4, "little")  # enum tag
    name, decoded = T.VOTE_INSTRUCTION.loads(enc)
    assert name == "vote" and decoded == vote
    # no-timestamp form is 1 byte shorter at the tail
    enc2 = T.VOTE.encode(T.Vote(slots=[1], hash=b"x" * 32))
    assert enc2[-1:] == b"\x00"
    with pytest.raises(T.CodecError, match="unknown enum tag"):
        T.VOTE_INSTRUCTION.loads((99).to_bytes(4, "little"))


def test_slot_hashes():
    shs = [T.SlotHash(3, b"a" * 32), T.SlotHash(2, b"b" * 32)]
    assert T.SLOT_HASHES.loads(T.SLOT_HASHES.encode(shs)) == shs


def test_legacy_contact_info_roundtrip():
    a = T.sockaddr_v4("127.0.0.1", 8001)
    ci = T.LegacyContactInfo(
        id=b"I" * 32, gossip=a, tvu=a, tvu_forwards=a, repair=a, tpu=a,
        tpu_forwards=a, tpu_vote=a, rpc=a, rpc_pubsub=a, serve_repair=a,
        wallclock=42, shred_version=7,
    )
    enc = T.LEGACY_CONTACT_INFO.encode(ci)
    out = T.LEGACY_CONTACT_INFO.loads(enc)
    assert out == ci
    # v4 socket wire shape: u32 tag 0 | 4 ip bytes | u16 port
    assert T.SOCKET_ADDR.encode(a) == bytes(4) + bytes([127, 0, 0, 1]) + (
        8001
    ).to_bytes(2, "little")
