"""Snapshot-over-HTTP: serve a snapshot dir, download with integrity
guards, cold-boot a funk from a peer (full + incremental)."""

import hashlib
import os

import pytest

from firedancer_tpu.flamenco import snapshot as snap
from firedancer_tpu.flamenco import snapshot_http as sh
from firedancer_tpu.flamenco.runtime import acct_build
from firedancer_tpu.funk.funk import Funk


def _funk_with(n, salt=b"a"):
    f = Funk()
    for i in range(n):
        f.rec_insert(None, hashlib.sha256(salt + bytes([i])).digest(),
                     acct_build(1000 + i))
    return f


@pytest.fixture
def peer(tmp_path):
    d = str(tmp_path / "snaps")
    os.makedirs(d)
    funk = _funk_with(20)
    snap.snapshot_write(
        funk, os.path.join(d, sh.full_snapshot_name(100)), slot=100
    )
    # incremental on top: one account changed, one added, one removed
    base = {k: funk.rec_query(None, k) for k in funk.rec_keys(None)}
    keys = sorted(base)
    funk.rec_insert(None, keys[0], acct_build(9_999))
    funk.rec_insert(None, hashlib.sha256(b"new").digest(), acct_build(5))
    funk.rec_remove(None, keys[1])
    snap.snapshot_write(
        funk, os.path.join(d, sh.incremental_snapshot_name(100, 140)),
        slot=140, base=base, base_slot=100,
    )
    srv = sh.SnapshotServer(d)
    yield srv, funk
    srv.close()


def test_bootstrap_from_peer(peer, tmp_path):
    srv, src_funk = peer
    dest = str(tmp_path / "boot")
    funk, man, (full, inc) = sh.bootstrap_from_peer(srv.addr, dest)
    assert man.slot == 140 and man.base_slot == 100
    assert inc is not None and os.path.exists(inc)
    # booted state == the peer's live state, removals included
    want = {k: src_funk.rec_query(None, k)
            for k in src_funk.rec_keys(None)}
    got = {k: funk.rec_query(None, k) for k in funk.rec_keys(None)}
    assert got == want


def test_download_rejects_truncated(peer, tmp_path):
    """A peer that closes mid-body must not leave a usable file."""
    import socket
    import threading

    srv, _ = peer
    # a fake peer that sends a bigger Content-Length than it delivers
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)

    def fake_peer():
        conn, _a = lsock.accept()
        conn.recv(4096)
        conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 1000\r\n\r\n")
        conn.sendall(b"x" * 100)
        conn.close()

    t = threading.Thread(target=fake_peer, daemon=True)
    t.start()
    dest = str(tmp_path / "dl")
    with pytest.raises(sh.SnapshotHttpError, match="closed at"):
        sh.download_snapshot(lsock.getsockname(), "snapshot.tar.zst", dest)
    t.join()
    lsock.close()
    assert os.listdir(dest) == []  # no partial file survives


def test_server_path_rules(peer, tmp_path):
    srv, _ = peer
    # traversal / junk names 404
    for bad in ("../etc/passwd", "snapshot.tar.gz", "x.tar.zst"):
        with pytest.raises(sh.SnapshotHttpError, match="404"):
            sh.download_snapshot(srv.addr, bad, str(tmp_path / "x"))
    # exact name works
    p = sh.download_snapshot(srv.addr, sh.full_snapshot_name(100),
                             str(tmp_path / "y"))
    man, accounts = snap.snapshot_read(p)
    assert man.slot == 100 and len(accounts) == 20


def test_full_only_peer(tmp_path):
    """A peer without incrementals still boots (404 tolerated)."""
    d = str(tmp_path / "only_full")
    os.makedirs(d)
    funk = _funk_with(5, salt=b"b")
    snap.snapshot_write(
        funk, os.path.join(d, sh.full_snapshot_name(7)), slot=7
    )
    srv = sh.SnapshotServer(d)
    try:
        got, man, (_full, inc) = sh.bootstrap_from_peer(
            srv.addr, str(tmp_path / "boot2")
        )
        assert man.slot == 7 and inc is None
        assert len(got.rec_keys(None)) == 5
    finally:
        srv.close()


def test_download_rejects_hash_mismatch(tmp_path):
    """A peer advertising a sha256 that doesn't match the bytes it sends
    (corruption, truncating middlebox) must be rejected."""
    import pytest

    from firedancer_tpu.flamenco.snapshot_http import (
        SnapshotHttpError, download_snapshot,
    )
    from firedancer_tpu.protocol import http as H

    blob = b"not really a snapshot" * 100

    def lying_handler(req, _body):
        return H.build_response(
            200, blob, content_type="application/octet-stream",
            headers=[("x-snapshot-sha256", "00" * 32),
                     ("x-snapshot-name", "snapshot-5.tar.zst")],
        )

    srv = H.MiniServer(lying_handler)
    try:
        with pytest.raises(SnapshotHttpError, match="hash mismatch"):
            download_snapshot(srv.addr, "snapshot.tar.zst",
                              str(tmp_path / "dl"))
        import os
        assert not os.listdir(tmp_path / "dl")  # nothing left behind
    finally:
        srv.close()


def test_server_streams_with_hash_and_name(tmp_path):
    """The server streams archives (never whole-file reads) and
    advertises canonical name + content hash; the client verifies and
    renames alias downloads to the canonical name."""
    import hashlib
    import os

    from firedancer_tpu.flamenco.snapshot_http import (
        SnapshotServer, download_snapshot,
    )

    sdir = tmp_path / "srv"
    os.makedirs(sdir)
    blob = os.urandom(3 << 20)  # > one 1 MiB stream chunk
    with open(sdir / "snapshot-42.tar.zst", "wb") as f:
        f.write(blob)
    srv = SnapshotServer(str(sdir))
    try:
        got = download_snapshot(srv.addr, "snapshot.tar.zst",
                                str(tmp_path / "dl"))
        assert os.path.basename(got) == "snapshot-42.tar.zst"
        with open(got, "rb") as f:
            data = f.read()
        assert hashlib.sha256(data).digest() == hashlib.sha256(blob).digest()
    finally:
        srv.close()


def test_download_rejects_cross_kind_advertised_name(tmp_path):
    """A peer answering the incremental alias with a FULL snapshot name
    (or any mismatched name) must be rejected — the advertised name is
    peer input and must not choose arbitrary destination filenames."""
    import pytest

    from firedancer_tpu.flamenco.snapshot_http import (
        SnapshotHttpError, download_snapshot,
    )
    from firedancer_tpu.protocol import http as H

    def evil_handler(req, _body):
        return H.build_response(
            200, b"x" * 64, content_type="application/octet-stream",
            headers=[("x-snapshot-name", "snapshot-42.tar.zst")],
        )

    srv = H.MiniServer(evil_handler)
    try:
        with pytest.raises(SnapshotHttpError, match="bad name"):
            download_snapshot(srv.addr, "incremental-snapshot.tar.zst",
                              str(tmp_path / "dl"))
    finally:
        srv.close()
