"""Parser fuzzing (the reference fuzzes every hand-written parser on its
attack surface: fuzz_txn_parse.c, fuzz_json_lex.c, fuzz_http.c,
fuzz_quic_wire.c, fuzz_gossip.c, fuzz_sbpf_loader.c + corpus/ seeds; see
SURVEY §4.5).  This build owns the same parsers in Python — every target
here must satisfy two properties on arbitrary bytes:

  1. no untyped escape: only the documented return (None/typed error) —
     anything else is a remote crash of the owning stage;
  2. differential agreement where two implementations exist (python vs
     native C++ txn parser).

Bounded for CI; crank FDTPU_FUZZ_EXAMPLES (e.g. 100000) for deep runs —
scripts/fuzz_deep.sh does exactly that target by target.

Structure-aware inputs: each target mixes raw random bytes with
mutations of a VALID seed message (bit flips, truncations, splices) so
coverage reaches past the outer length checks — the same trick as the
reference's seed corpora.
"""

from __future__ import annotations

import os
import struct

import pytest

# gate, don't error: hypothesis is an optional dev dependency — on boxes
# without it (this image bakes only the jax toolchain) the module must
# SKIP at collection, not break the whole suite's collection.  Deep-fuzz
# hosts install hypothesis and run scripts/fuzz_deep.sh.
pytest.importorskip(
    "hypothesis",
    reason="hypothesis not installed (optional fuzz-tier dependency; "
           "see scripts/fuzz_deep.sh)",
)

from hypothesis import HealthCheck, given, settings, strategies as st

MAX_EXAMPLES = int(os.environ.get("FDTPU_FUZZ_EXAMPLES", "250"))

FUZZ = settings(
    max_examples=MAX_EXAMPLES,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow, HealthCheck.data_too_large,
                           HealthCheck.filter_too_much],
)

raw = st.binary(min_size=0, max_size=1400)


def mutated(seed: bytes):
    """Strategy: the seed with flips/truncations/splices applied."""

    def apply(draw_ops):
        data = bytearray(seed)
        for op, a, b in draw_ops:
            if not data:
                break
            if op == 0:  # flip byte
                data[a % len(data)] ^= b or 1
            elif op == 1:  # truncate
                del data[a % (len(data) + 1):]
            elif op == 2:  # duplicate a slice
                i = a % len(data)
                data[i:i] = data[i : i + (b % 64)]
            elif op == 3:  # overwrite with 0xff run
                i = a % len(data)
                data[i : i + (b % 16)] = b"\xff" * (b % 16)
        return bytes(data)

    return st.lists(
        st.tuples(st.integers(0, 3), st.integers(0, 2**31), st.integers(0, 255)),
        min_size=0, max_size=12,
    ).map(apply)


# -- seeds --------------------------------------------------------------------


def _vote_txn() -> bytes:
    from firedancer_tpu.protocol.txn import vote_txn

    return vote_txn(b"\x01" * 32, b"\x02" * 32, 7, b"\x03" * 32)


def _gossip_msg() -> bytes:
    from firedancer_tpu.flamenco import gossip_wire as gw

    from firedancer_tpu.flamenco import types as T

    def sock(port):
        return ("v4", T.SockAddr(b"\x7f\x00\x00\x01", port))

    val = gw.contact_info_value(
        b"\x07" * 32,
        gossip=sock(8001), tvu=sock(8002), repair=sock(8003),
        tpu=sock(8004), wallclock=123,
    )
    return gw.encode_message("push_message", (b"\x05" * 32, [val]))


def _repair_req() -> bytes:
    from firedancer_tpu.flamenco import repair_wire as rw

    hdr = rw.RepairRequestHeader(
        signature=bytes(64), sender=b"\x01" * 32, recipient=b"\x04" * 32,
        timestamp=1, nonce=77,
    )
    return rw.sign_request(
        b"\x01" * 32, "window_index",
        rw.WindowIndex(header=hdr, slot=5, shred_index=9),
    )


# -- txn parse: no-crash + native differential --------------------------------


@FUZZ
@given(st.one_of(raw, mutated(_vote_txn())))
def test_fuzz_txn_parse(data):
    from firedancer_tpu.protocol import txn as ft

    t = ft.txn_parse(data)
    if t is not None:
        # parsed descriptor invariants the verify stage relies on
        assert 0 < t.signature_cnt <= 16
        assert t.message_off <= len(data)
        list(t.signatures(data))
        list(t.signers(data))


@FUZZ
@given(st.one_of(raw, mutated(_vote_txn())))
def test_fuzz_txn_parse_native_differential(data):
    from firedancer_tpu.protocol import txn as ft

    try:
        from firedancer_tpu.protocol import txn_native as fn
    except Exception:
        import pytest

        pytest.skip("native parser unavailable")
    py = ft.txn_parse(data)
    nat = fn.txn_parse_native(data)
    assert (py is None) == (nat is None), (
        f"py={'ok' if py else 'reject'} native={'ok' if nat else 'reject'}"
    )
    if py is not None and nat is not None:
        assert py.signature_cnt == nat.signature_cnt
        assert py.message_off == nat.message_off
        assert py.acct_addr_cnt == nat.acct_addr_cnt


# -- jsonlex ------------------------------------------------------------------


@FUZZ
@given(raw)
def test_fuzz_jsonlex_bytes(data):
    from firedancer_tpu.protocol import jsonlex as J

    try:
        J.loads(data)
    except J.JsonError:
        pass
    except (UnicodeDecodeError, RecursionError):
        pass  # typed: input not UTF-8 / beyond depth cap


@FUZZ
@given(st.recursive(
    st.none() | st.booleans() | st.integers(-(2**53), 2**53)
    | st.floats(allow_nan=False, allow_infinity=False) | st.text(max_size=40),
    lambda children: st.lists(children, max_size=5)
    | st.dictionaries(st.text(max_size=10), children, max_size=5),
    max_leaves=25,
))
def test_fuzz_jsonlex_roundtrip(value):
    from firedancer_tpu.protocol import jsonlex as J

    assert J.loads(J.dumps(value)) == value


# -- http ---------------------------------------------------------------------


@FUZZ
@given(st.one_of(
    raw,
    mutated(b"POST /rpc HTTP/1.1\r\nHost: x\r\nContent-Length: 2\r\n\r\nhi"),
))
def test_fuzz_http_request(data):
    from firedancer_tpu.protocol import http as H

    try:
        r = H.parse_request(data)
    except H.HttpError:
        return  # typed reject: MiniServer answers 400 (http.py:261)
    if r is not None and r is not H.NEED_MORE:
        assert isinstance(r.method, str)


@FUZZ
@given(st.one_of(
    raw,
    mutated(b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nhi"),
))
def test_fuzz_http_response(data):
    from firedancer_tpu.protocol import http as H

    try:
        H.parse_response(data)
    except H.HttpError:
        pass  # typed reject: clients drop the connection


# -- quic frames + packet open ------------------------------------------------


@FUZZ
@given(st.one_of(raw, mutated(bytes([0x06, 0x00, 0x04]) + b"\x01" * 4)))
def test_fuzz_quic_frames(data):
    from firedancer_tpu.waltz import quic as Q

    try:
        for _ev in Q.parse_frames(data):
            pass
    except Q.QuicError:
        pass


@FUZZ
@given(raw, st.integers(0, 3))
def test_fuzz_quic_open_packet(data, largest_shift):
    """Untrusted datagram bytes: open_packet must return or raise
    QuicError — never escape with struct/index errors (a spoofable UDP
    datagram would kill the ingress stage; ADVICE r3 high finding)."""
    from firedancer_tpu.waltz import quic as Q

    if not data:
        return
    try:
        Q.open_packet(
            data, 0, lambda lvl, dcid: None, short_dcid_len=8,
            largest_for_level=lambda lvl: (1 << (16 * largest_shift)) - 1,
        )
    except Q.QuicError:
        pass
    except IndexError:
        pass  # first-byte probe of an empty tail; caller guards length>0


# -- gossip / repair ----------------------------------------------------------


@FUZZ
@given(st.one_of(raw, mutated(_gossip_msg())))
def test_fuzz_gossip_decode(data):
    from firedancer_tpu.flamenco import gossip_wire as gw

    m = gw.decode_message(data)
    if m is not None:
        name, _payload = m
        assert isinstance(name, str)


@FUZZ
@given(st.one_of(raw, mutated(_repair_req())))
def test_fuzz_repair_verify(data):
    from firedancer_tpu.flamenco import repair_wire as rw

    rw.verify_request(data)
    rw.decode_response(data)


# -- sbpf ELF loader ----------------------------------------------------------


def _tiny_elf() -> bytes:
    from firedancer_tpu.protocol import sbpf as S

    try:
        return S.build_minimal_elf(b"\x95\x00\x00\x00\x00\x00\x00\x00")
    except AttributeError:
        import glob

        for p in glob.glob("tests/data/*.so") + glob.glob("tests/*.so"):
            with open(p, "rb") as f:
                return f.read()
        return b"\x7fELF" + bytes(60)


@FUZZ
@given(st.one_of(raw, mutated(_tiny_elf())))
def test_fuzz_sbpf_load(data):
    from firedancer_tpu.protocol import sbpf as S

    try:
        S.load(data)
    except S.SbpfError:
        pass


# -- shred --------------------------------------------------------------------


@FUZZ
@given(st.one_of(raw, st.binary(min_size=1200, max_size=1229)))
def test_fuzz_shred_parse(data):
    from firedancer_tpu.protocol import shred as sh

    s = sh.parse(data)
    if s is not None:
        assert s.index >= 0


# -- bincode types (snapshot/gossip fidelity layer) ---------------------------


@FUZZ
@given(raw)
def test_fuzz_bincode_types(data):
    from firedancer_tpu.flamenco import types as T

    for codec in (T.CLOCK, T.RENT, T.EPOCH_SCHEDULE):
        try:
            codec.decode(data, 0)
        except (T.CodecError, ValueError, struct.error):
            pass


# -- toml ---------------------------------------------------------------------


@FUZZ
@given(st.one_of(
    raw,
    mutated(b'[a]\nx = 1\ny = "s"\narr = [1, 2.5, true]\n[[b]]\nk = 0x1f\n'),
    st.text(max_size=300).map(lambda s: s.encode()),
))
def test_fuzz_toml(data):
    """Own parser: typed reject or a dict, never an untyped escape; and
    whenever BOTH parsers accept, the values agree (differential)."""
    import tomllib

    from firedancer_tpu.protocol import toml as T

    try:
        ours = T.loads(data)
    except T.TomlError:
        return
    except (UnicodeDecodeError, RecursionError):
        return
    try:
        ref = tomllib.loads(data.decode("utf-8"))
    except Exception:
        return  # we accept, tomllib rejects: divergence tolerated only
        # for content tomllib cannot represent — asserted via samples
    # scrub NaN (NaN != NaN breaks equality) before comparing
    def scrub(v):
        if isinstance(v, float) and v != v:
            return "nan"
        if isinstance(v, dict):
            return {k: scrub(x) for k, x in v.items()}
        if isinstance(v, list):
            return [scrub(x) for x in v]
        return v

    if all(not _has_date(v) for v in ref.values()):
        assert scrub(ours) == scrub(ref)


def _has_date(v):
    import datetime

    if isinstance(v, (datetime.date, datetime.time, datetime.datetime)):
        return True
    if isinstance(v, dict):
        return any(_has_date(x) for x in v.values())
    if isinstance(v, list):
        return any(_has_date(x) for x in v)
    return False
