"""SHA-256 batch op and PoH chain tests, differential vs hashlib."""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from firedancer_tpu.ops import sha256 as fsha
from firedancer_tpu.runtime import poh


def cols(rows, n):
    a = np.zeros((n, len(rows)), dtype=np.int32)
    for i, r in enumerate(rows):
        a[: len(r), i] = np.frombuffer(r, dtype=np.uint8)
    return jnp.asarray(a)


def test_sha256_msg_vs_hashlib(rng):
    # lengths straddling block/pad boundaries: 0, 1, 55, 56, 63, 64, 119, 120
    lens = [0, 1, 55, 56, 63, 64, 119, 120, 128, 200]
    msgs = [rng.bytes(l) for l in lens]
    max_len = 256
    out = np.asarray(
        jax.jit(lambda m, l: fsha.sha256_msg(m, l, max_len))(
            cols(msgs, max_len), jnp.asarray(np.array(lens, dtype=np.int32))
        )
    )
    for i, m in enumerate(msgs):
        assert out[:, i].astype(np.uint8).tobytes() == hashlib.sha256(m).digest(), lens[i]


def test_sha256_iter32_vs_hashlib(rng):
    b = 4
    starts = [rng.bytes(32) for _ in range(b)]
    n = 37
    got = np.asarray(fsha.sha256_iter32(cols(starts, 32), n))
    for i, s in enumerate(starts):
        h = s
        for _ in range(n):
            h = hashlib.sha256(h).digest()
        assert got[:, i].astype(np.uint8).tobytes() == h


def test_sha256_mix32_vs_hashlib(rng):
    b = 3
    states = [rng.bytes(32) for _ in range(b)]
    mixes = [rng.bytes(32) for _ in range(b)]
    got = np.asarray(
        jax.jit(fsha.sha256_mix32)(cols(states, 32), cols(mixes, 32))
    )
    for i in range(b):
        assert (
            got[:, i].astype(np.uint8).tobytes()
            == hashlib.sha256(states[i] + mixes[i]).digest()
        )


def test_poh_chain_and_tpu_segment_verify(rng):
    # generate a chain on host with mixins, then batch-verify the pure
    # append segments between records on device
    chain = poh.PohChain(hash=hashlib.sha256(b"genesis").digest())
    seg = 25
    checkpoints = [(0, chain.hash)]
    for k in range(6):
        chain.append(seg)
        checkpoints.append((chain.hashcnt, chain.hash))
    starts = [h for _, h in checkpoints[:-1]]
    ends = [h for _, h in checkpoints[1:]]
    ok = poh.verify_segments_tpu(starts, seg, ends)
    assert ok.all()
    # corrupt one end: only that segment fails
    bad_ends = list(ends)
    bad_ends[3] = bytes(32)
    ok = poh.verify_segments_tpu(starts, seg, bad_ends)
    assert list(ok) == [True, True, True, False, True, True]
    # host fallback agrees
    assert poh.verify_segments_host(starts, [seg] * 6, ends) == [True] * 6


def test_poh_mixin_records():
    chain = poh.PohChain(hash=bytes(32))
    chain.append(10)
    chain.mixin(b"\x01" * 32)
    chain.tick()
    assert chain.hashcnt == 11
    assert len(chain.records) == 2
    assert chain.records[0].mixin == b"\x01" * 32
    assert chain.records[1].mixin is None
    # mixin semantics: sha256(h || mix)
    h = poh.poh_append(bytes(32), 10)
    assert chain.records[0].hash == hashlib.sha256(h + b"\x01" * 32).digest()
