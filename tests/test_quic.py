"""QUIC + TLS 1.3: RFC 9001 Appendix-A key-derivation conformance,
varints, packet seal/open round-trips, the full handshake over
in-memory datagrams, and stream delivery into reassembly."""

import pytest

from firedancer_tpu.waltz import quic, tls13


# -- RFC 9001 Appendix A: Initial keys for DCID 0x8394c8f03e515708 ------------


def test_rfc9001_initial_secrets():
    dcid = bytes.fromhex("8394c8f03e515708")
    csec, ssec = quic.initial_secrets(dcid)
    assert csec == bytes.fromhex(
        "c00cf151ca5be075ed0ebfb5c80323c42d6b7db67881289af4008f1f6c357aea"
    )
    assert ssec == bytes.fromhex(
        "3c199828fd139efd216c155ad844cc81fb82fa8d7446fa7d78be803acdda951b"
    )
    keys = quic.Keys.from_secret(csec)
    assert keys.iv == bytes.fromhex("fa044b2f42a3fd3b46fb255c")
    # hp key check: known value drives the Aes schedule; verify by
    # deriving again at the label layer
    assert tls13.hkdf_expand_label(csec, "quic key", b"", 16) == bytes.fromhex(
        "1f369613dd76d5467730efcbe3b1a22d"
    )
    assert tls13.hkdf_expand_label(csec, "quic hp", b"", 16) == bytes.fromhex(
        "9f50449e04a0e810283a1e9933adedd2"
    )


def test_varint_roundtrip():
    for v in (0, 63, 64, 16383, 16384, (1 << 30) - 1, 1 << 30, (1 << 62) - 1):
        enc = quic.varint_encode(v)
        dec, off = quic.varint_decode(enc, 0)
        assert (dec, off) == (v, len(enc))
    with pytest.raises(quic.QuicError):
        quic.varint_encode(1 << 62)
    # RFC 9000 §A.1 example: 0xc2197c5eff14e88c -> 151288809941952652
    dec, _ = quic.varint_decode(bytes.fromhex("c2197c5eff14e88c"), 0)
    assert dec == 151_288_809_941_952_652


def test_packet_seal_open_roundtrip():
    dcid = b"\x11" * 8
    csec, ssec = quic.initial_secrets(dcid)
    tx = quic.Keys.from_secret(csec)
    rx = quic.Keys.from_secret(csec)
    payload = quic.crypto_frame(0, b"hello quic") + bytes(20)
    pkt = quic.seal_packet(tx, level=quic.INITIAL, dcid=dcid, scid=b"\x22" * 8,
                           pn=7, payload=payload)
    out, end = quic.open_packet(pkt, 0, lambda lvl, d: rx, short_dcid_len=8)
    assert end == len(pkt)
    assert out.pn == 7 and out.payload == payload
    assert out.dcid == dcid and out.scid == b"\x22" * 8
    # tampering breaks authentication
    bad = bytearray(pkt)
    bad[-1] ^= 1
    with pytest.raises(quic.QuicError, match="authentication"):
        quic.open_packet(bytes(bad), 0, lambda lvl, d: rx, short_dcid_len=8)


def _handshake_pair(**kw):
    identity = bytes(range(32))
    from firedancer_tpu.ops.ref import ed25519_ref

    server = quic.Connection.server_new(identity, transport_params=b"srv-tp")
    client = quic.Connection.client_new(
        expected_peer=ed25519_ref.public_key(identity),
        transport_params=b"cli-tp", **kw,
    )
    # drive datagrams until both sides are established (reliable pipe)
    for _ in range(6):
        for dg in client.flush():
            server.receive(dg)
        for dg in server.flush():
            client.receive(dg)
        if client.established and server.established:
            break
    return client, server


def test_full_handshake_and_stream():
    client, server = _handshake_pair()
    assert client.established and server.established
    # transport params crossed over
    assert client.tls.peer_transport_params == b"srv-tp"
    assert server.tls.peer_transport_params == b"cli-tp"

    # client->server unidirectional stream (id 2): a txn payload
    txn = b"\xAB" * 700
    client.send_stream(2, txn[:400])
    client.send_stream(2, txn[400:], fin=True)
    got = []
    for dg in client.flush():
        events = server.receive(dg)
        got += server.receive_stream_events(events)
    data = b"".join(chunk for _, chunk, _ in got)
    assert data == txn
    assert got[-1][2] is True  # fin seen


def test_handshake_rejects_wrong_identity():
    identity = bytes(range(32))
    wrong_pin = b"\x99" * 32
    server = quic.Connection.server_new(identity)
    client = quic.Connection.client_new(expected_peer=wrong_pin)
    with pytest.raises(tls13.TlsError, match="pinned"):
        for _ in range(4):
            for dg in client.flush():
                server.receive(dg)
            for dg in server.flush():
                client.receive(dg)


def test_out_of_order_stream_reassembly():
    client, server = _handshake_pair()
    ev = [
        quic.StreamEvent(2, 100, b"B" * 50, False),
        quic.StreamEvent(2, 0, b"A" * 100, False),
        quic.StreamEvent(2, 150, b"C" * 10, True),
    ]
    chunks = server.receive_stream_events(ev)
    data = b"".join(c for _, c, _ in chunks)
    assert data == b"A" * 100 + b"B" * 50 + b"C" * 10


def test_client_initial_is_padded():
    client = quic.Connection.client_new()
    dgs = client.flush()
    assert dgs and len(dgs[0]) >= 1200  # §14.1 anti-amplification floor


# -- connection migration (RFC 9000 §9) ---------------------------------------


def test_connection_migration_path_validation():
    """An established client moves to a new source address: the server
    finds the conn by CID, validates the new path with PATH_CHALLENGE /
    PATH_RESPONSE, and subsequent replies follow the client."""
    import hashlib
    import socket as _socket
    import threading
    import time as _time

    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.runtime.benchg import gen_transfer_pool
    from firedancer_tpu.runtime.net import QuicIngressStage, QuicTxnClient
    from firedancer_tpu.tango import shm as _shm

    import os as _os

    uid = f"{_os.getpid()}_{int(_time.monotonic_ns() % 1_000_000)}"
    out_link = _shm.ShmLink.create(f"fdtpu_mig_{uid}", depth=256, mtu=1232)
    identity = hashlib.sha256(b"mig-id").digest()
    ingress = QuicIngressStage(
        "quic", outs=[_shm.Producer(out_link)], rx_burst=32,
        identity_secret=identity,
    )
    sink = _shm.Consumer(out_link, lazy=8)
    pool = gen_transfer_pool(4, seed=b"mig")
    try:
        box = {}

        def connect():
            box["c"] = QuicTxnClient(
                ingress.addr, expected_peer=ref.public_key(identity)
            )

        t = threading.Thread(target=connect)
        t.start()
        deadline = _time.monotonic() + 60
        while t.is_alive() and _time.monotonic() < deadline:
            ingress.run_once()
        t.join(1)
        client = box["c"]
        assert client.conn.established

        got = []

        def pump(n=200):
            for _ in range(n):
                ingress.run_once()
                client._drain_rx()
                client._flush_out()
                res = sink.poll()
                if isinstance(res, tuple):
                    got.append(res[1])

        client.send_txn(pool[0])
        pump()
        assert len(got) == 1

        # MIGRATE: same Connection, brand-new UDP socket
        old_sock = client.sock
        client.sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        client.sock.settimeout(0.05)
        client.send_txn(pool[1])
        pump()
        # server challenged the new path; the client conn auto-queued the
        # response which _flush_out sent from the new socket
        assert ingress.metrics.get("path_challenge_tx") >= 1
        deadline = _time.monotonic() + 30
        while ingress.metrics.get("migrated") == 0 and \
                _time.monotonic() < deadline:
            client.send_txn(pool[2])
            pump()
        assert ingress.metrics.get("migrated") == 1
        # post-migration traffic flows on the new path
        client.send_txn(pool[3])
        pump()
        assert len(got) >= 3
        old_sock.close()
    finally:
        ingress.close()
        out_link.close()
        out_link.unlink()
