"""Slot-clock plane: deadline geometry, paced PoH sealing, the missed-
slot outcome, pack's deadline block close + carryover + load shedding,
and the compressed-cadence cooperative pipeline run (the acceptance
surface of ISSUE 14: every slot seals at its deadline with bounded
jitter, the unscheduled tail carries over with zero loss, an induced
overrun yields slot_missed + clean continuation)."""

import time

import pytest

from firedancer_tpu.runtime.slot_clock import (
    SlotClock,
    SlotClockCfg,
    resolve_clock,
)
from firedancer_tpu.tango import shm
from firedancer_tpu.utils import metrics as fm

MS = 1_000_000  # ns


def vclock(t, **kw):
    """A SlotClock over fully virtual time: t is a 1-element list of ns."""
    kw.setdefault("slot_ms", 100.0)
    kw.setdefault("slot0", 1)
    kw.setdefault("ticks_per_slot", 4)
    kw.setdefault("miss_grace_frac", 0.25)
    cfg = SlotClockCfg(t0_ns=0, **kw)
    return SlotClock(cfg, now_fn=lambda: t[0])


# -- geometry -----------------------------------------------------------------


def test_slot_clock_geometry():
    t = [0]
    c = vclock(t, n_slots=5)
    assert c.slot_at(0) == 1
    assert c.slot_at(99 * MS) == 1
    assert c.slot_at(100 * MS) == 2
    assert c.slot_at(450 * MS) == 5
    assert c.start_of(3) == 200 * MS
    assert c.deadline_of(3) == 300 * MS
    assert c.remaining_ns(1, 40 * MS) == 60 * MS
    # ticks 1..4 of slot 1 due at 25/50/75/100ms
    assert c.ticks_due(1, 0) == 0
    assert c.ticks_due(1, 24 * MS) == 0
    assert c.ticks_due(1, 25 * MS) == 1
    assert c.ticks_due(1, 99 * MS) == 3
    assert c.ticks_due(1, 500 * MS) == 4  # clamped
    assert c.tick_deadline(2, 1) == 125 * MS
    # grace: missed only past deadline + 25ms
    assert not c.missed(1, 100 * MS)
    assert not c.missed(1, 125 * MS)
    assert c.missed(1, 126 * MS)
    # window: 5 slots -> handoff at 500ms
    assert c.last_slot() == 5
    assert c.window_end_ns() == 500 * MS
    assert c.in_window(5) and not c.in_window(6)
    assert not c.window_done(499 * MS) and c.window_done(500 * MS)


def test_slot_clock_pre_anchor_clamps_to_slot0():
    t = [0]
    cfg = SlotClockCfg(slot_ms=100.0, t0_ns=50 * MS)
    c = SlotClock(cfg, now_fn=lambda: t[0])
    # the boot-grace period belongs to the first slot
    assert c.slot_at(0) == cfg.slot0
    assert c.ticks_due(cfg.slot0, 0) == 0


def test_cfg_anchoring_idempotent_and_picklable():
    import pickle

    cfg = SlotClockCfg(slot_ms=50.0, n_slots=3)
    a = cfg.anchored(1.0, now_ns=1000)
    assert a.t0_ns == 1000 + int(1e9)
    assert a.anchored(5.0) is a  # already anchored: no re-anchor
    assert pickle.loads(pickle.dumps(a)) == a
    with pytest.raises(TypeError):
        resolve_clock(object())
    assert resolve_clock(None) is None


def test_slot_clock_rejects_degenerate_geometry():
    with pytest.raises(ValueError):
        SlotClock(SlotClockCfg(slot_ms=0.0, t0_ns=0))
    with pytest.raises(ValueError):
        SlotClock(SlotClockCfg(ticks_per_slot=0, t0_ns=0))


# -- paced poh ----------------------------------------------------------------


def make_poh(t, **kw):
    from firedancer_tpu.runtime.poh_stage import PohStage

    clock = vclock(t, **kw)
    uid = shm.fresh_uid("tsc")
    link = shm.ShmLink.create(f"fdtpu_ps_{uid}", depth=256, mtu=65536)
    poh = PohStage("poh", outs=[shm.Producer(link)], clock=clock)
    poh.require_credit = True
    return poh, link, clock


def drive(poh, t, upto_ms, step_ms=5, iters=30):
    for ms in range(int(t[0] / MS), upto_ms + 1, step_ms):
        t[0] = ms * MS
        for _ in range(iters):
            poh.run_once()


def test_poh_ticks_paced_to_the_deadline():
    t = [0]
    poh, link, clock = make_poh(t, n_slots=2)
    sink = shm.Consumer(link, lazy=4)
    try:
        # halfway through slot 1 exactly 2 of 4 ticks may have landed
        drive(poh, t, 50)
        assert poh.metrics.get("ticks") == 2
        # a stalled wall clock emits nothing no matter how hot the loop
        for _ in range(2000):
            poh.run_once()
        assert poh.metrics.get("ticks") == 2
        drive(poh, t, 99)
        assert poh.metrics.get("ticks") == 3  # final tick seals AT 100ms
        drive(poh, t, 100)
        assert poh.metrics.get("ticks") == 4
        assert poh.metrics.get("slots_sealed") == 1
        assert poh.slot == 2
    finally:
        del sink
        link.close()
        link.unlink()


def test_poh_seal_regardless_of_pending_load_and_window_close():
    t = [0]
    poh, link, clock = make_poh(t, n_slots=2)
    try:
        # jump straight to the deadline: every tick of slot 1 must land
        # NOW (sealed at the boundary regardless of how it was paced)
        t[0] = 100 * MS
        for _ in range(50):
            poh.run_once()
        assert poh.metrics.get("slots_sealed") == 1
        assert poh.metrics.get("ticks") == 4
        # slot 2 seals at its own deadline and the window closes: the
        # handoff fires on the schedule, not on drain
        drive(poh, t, 200)
        assert poh.metrics.get("slots_sealed") == 2
        assert poh.window_closed
        assert poh.slots_done() == 2
        # past the window nothing ever ticks again
        drive(poh, t, 400)
        assert poh.metrics.get("ticks") == 8
    finally:
        link.close()
        link.unlink()


def test_poh_missed_slot_is_a_value_not_a_hang():
    t = [0]
    poh, link, clock = make_poh(t, n_slots=6)
    try:
        drive(poh, t, 100)  # slot 1 seals clean
        assert poh.metrics.get("slots_sealed") == 1
        # freeze across the boundaries of slots 2 and 3 (plus grace)
        t[0] = 330 * MS
        for _ in range(50):
            poh.run_once()
        assert poh.metrics.get("slot_missed") == 2
        assert poh.metrics.get("slot_skipped_ticks") == 8
        assert poh.slot == 4  # clean continuation at the scheduled slot
        # the flight ring carries one slot_missed record per slot
        missed_evs = [r for r in poh.recorder.records()
                      if r[1] == fm.EV_SLOT_MISSED]
        assert [r[2] for r in missed_evs] == [2, 3]
        # the rest of the window seals normally
        drive(poh, t, 600)
        assert poh.metrics.get("slots_sealed") == 4
        assert poh.window_closed
        assert poh.slots_done() == 6
    finally:
        link.close()
        link.unlink()


def test_poh_backpressure_past_grace_becomes_a_miss():
    """Credit starvation at the boundary: the consumer never drains, the
    ring fills, poh cannot land the final ticks — past the grace that is
    a MISSED slot and the stage moves on (never a hang, never a drop of
    the chain's continuity)."""
    from firedancer_tpu.runtime.poh_stage import PohStage

    t = [0]
    clock = vclock(t, n_slots=3)
    uid = shm.fresh_uid("tbp")
    link = shm.ShmLink.create(f"fdtpu_ps_{uid}", depth=4, mtu=65536)
    poh = PohStage("poh", outs=[shm.Producer(link)], clock=clock)
    poh.require_credit = True
    try:
        # nobody consumes: 4 credits total, slot 1's 4 ticks exhaust them
        drive(poh, t, 100)
        assert poh.metrics.get("slots_sealed") == 1
        # slot 2's ticks cannot publish (ring full); past grace -> miss
        drive(poh, t, 230)
        assert poh.metrics.get("slot_missed") >= 1
        hashcnt_at_miss = poh.chain.hashcnt
        # a consumer appears; the NEXT slot proceeds from the live chain
        sink = shm.Consumer(link, lazy=1)
        while isinstance(sink.poll(), tuple):
            pass
        for p in poh.outs:
            p.refresh_credits()
        drive(poh, t, 300)
        assert poh.slots_done() == 3
        assert poh.chain.hashcnt > hashcnt_at_miss
    finally:
        link.close()
        link.unlink()


# -- pack: deadline close, carryover, shedding --------------------------------


def _mk_pack_stage(t, clock_kw=None, **kw):
    from firedancer_tpu.runtime.pack_stage import PackStage

    clock = vclock(t, **(clock_kw or {}))
    uid = shm.fresh_uid("tpk")
    l_in = shm.ShmLink.create(f"fdtpu_pi_{uid}", depth=256, mtu=4096)
    l_out = shm.ShmLink.create(f"fdtpu_po_{uid}", depth=64, mtu=65536)
    l_done = shm.ShmLink.create(f"fdtpu_pd_{uid}", depth=64, mtu=64)
    stage = PackStage(
        "pack",
        ins=[shm.Consumer(l_in, lazy=8), shm.Consumer(l_done, lazy=8)],
        outs=[shm.Producer(l_out)],
        bank_cnt=1,
        clock=clock,
        **kw,
    )
    return stage, (l_in, l_out, l_done), clock


def _feed_txns(stage, l_in, n, seed=b"carry"):
    from firedancer_tpu.protocol import txn as ft
    from firedancer_tpu.runtime.benchg import gen_transfer_pool
    from firedancer_tpu.runtime.verify import encode_verified

    prod = shm.Producer(l_in)
    pool = gen_transfer_pool(n, seed=seed)
    for i, payload in enumerate(pool):
        desc = ft.txn_parse(payload)
        assert prod.try_publish(encode_verified(payload, desc), sig=i)
    for _ in range(n + 16):
        stage.run_once()


def test_pack_deadline_close_carries_tail_across_slots():
    t = [0]
    stage, links, clock = _mk_pack_stage(
        t, clock_kw={"slot_ms": 100.0},
        min_pending=10**9, mb_deadline_s=10**9, adaptive=False,
    )
    l_in, l_out, l_done = links
    try:
        _feed_txns(stage, l_in, 24)
        assert stage._pending_cnt() == 24
        # mid-slot: the absurd min_pending blocks scheduling entirely
        t[0] = 50 * MS
        for _ in range(20):
            stage.run_once()
        assert stage.metrics.get("microblocks") == 0
        # the slot's final stretch (last 25%): deadline-aware close
        # schedules aggressively — no accumulation games at the boundary
        t[0] = 80 * MS
        for _ in range(20):
            stage.run_once()
        assert stage.metrics.get("microblocks") >= 1
        first_slot_scheduled = stage.metrics.get("txn_scheduled")
        assert first_slot_scheduled > 0
        # cross the boundary: block accounting resets, NOTHING is lost —
        # the unscheduled tail is simply still pooled
        t[0] = 101 * MS
        for _ in range(5):
            stage.run_once()
        assert stage.metrics.get("blocks_closed") == 1
        assert stage.metrics.get("txn_dropped") == 0
        assert (stage._pending_cnt() + first_slot_scheduled) == 24
    finally:
        for link in links:
            link.close()
            link.unlink()


def test_pack_load_shed_at_the_deadline_python_lane():
    t = [0]
    stage, links, clock = _mk_pack_stage(
        t, clock_kw={"slot_ms": 100.0},
        min_pending=10**9, mb_deadline_s=10**9, adaptive=False,
        shed_keep=8,
    )
    l_in, l_out, l_done = links
    try:
        _feed_txns(stage, l_in, 24)
        assert stage._pending_cnt() == 24
        t[0] = 50 * MS  # mid-slot: no shedding yet
        for _ in range(5):
            stage.run_once()
        assert stage.metrics.get("txn_shed") == 0
        t[0] = 80 * MS  # the clock says the slot can't drain 24: shed
        stage.run_once()
        assert stage.metrics.get("txn_shed") == 16
        # the 8 survivors are either still pooled or already scheduled
        # by the same deadline-close posture — never lost
        assert (stage._pending_cnt()
                + stage.metrics.get("txn_scheduled")) == 8
        # shed events ride the flight ring
        assert any(r[1] == fm.EV_SLOT_SHED
                   for r in stage.recorder.records())
    finally:
        for link in links:
            link.close()
            link.unlink()


def test_pack_shed_drops_lowest_priority_first_and_spares_votes():
    from firedancer_tpu.pack.scheduler import Pack
    from firedancer_tpu.protocol import txn as ft
    from firedancer_tpu.runtime.benchg import gen_transfer_pool

    pack = Pack(bank_cnt=1, depth=64)
    pool = gen_transfer_pool(12, seed=b"shed")
    descs = []
    for payload in pool:
        d = ft.txn_parse(payload)
        assert pack.insert(payload, d)
        descs.append((payload, d))
    before = pack.pending_cnt()
    # the shed order is the pool tail: capture it, then shed
    tail = [o.first_sig() for o in pack._pending[-4:]]
    assert pack.shed_lowest(4) == 4
    assert pack.pending_cnt() == before - 4
    for sig in tail:
        assert sig not in pack._sigs
    # over-shedding is clamped, never an error
    assert pack.shed_lowest(10**6) == before - 4
    assert pack.pending_cnt() == 0


def test_native_pack_shed_parity():
    from firedancer_tpu.pack import scheduler_native as sn

    if not sn.available():
        pytest.skip("native pack .so unavailable")
    from firedancer_tpu.pack.scheduler import Pack
    from firedancer_tpu.protocol import txn as ft
    from firedancer_tpu.runtime.benchg import gen_transfer_pool

    py = Pack(bank_cnt=1, depth=64)
    nat = sn.NativePack(bank_cnt=1, depth=64)
    pool = gen_transfer_pool(16, seed=b"shednat")
    from firedancer_tpu.runtime.verify import encode_verified

    entries = []
    for i, payload in enumerate(pool):
        d = ft.txn_parse(payload)
        assert py.insert(payload, d)
        entries.append((encode_verified(payload, d), i + 1, 0))
    codes = nat.insert_burst(entries)
    assert codes == bytes([sn.INS_OK]) * len(entries)
    assert nat.pending_cnt() == py.pending_cnt() == 16
    assert nat.shed_lowest(5) == py.shed_lowest(5) == 5
    assert nat.pending_cnt() == py.pending_cnt() == 11
    # the survivors schedule identically: shed trimmed the same tail
    mb_py = py.schedule_next_microblock(0)
    res_nat = nat.schedule(0, mb_seq=0, any_pool=True)
    assert (res_nat is None) == (not mb_py)
    if mb_py:
        assert res_nat[1] == len(mb_py)
    nat.close()


# -- the compressed-cadence pipeline run (acceptance) -------------------------


@pytest.mark.slow  # ~24 s wall (real compressed clock + pipeline build);
# the cadence invariants each have focused tier-1 tests above, and the
# fused-stage clock run (test_poh_shred_fused) keeps an e2e clock test
# in tier-1
def test_leader_pipeline_under_compressed_cadence_zero_loss():
    """The cooperative leader pipeline against a real (compressed) wall
    clock: every slot seals at its deadline with bounded jitter, txns
    keep landing across the boundaries (the carryover contract — zero
    loss, regression-diffed against the clock-off run), and the window
    closes on the schedule."""
    from firedancer_tpu.models.leader import build_leader_pipeline

    N = 96
    n_slots = 4
    cfg = SlotClockCfg(slot_ms=150.0, slot0=1, ticks_per_slot=4,
                       n_slots=n_slots, miss_grace_frac=0.3)

    def run(clocked: bool):
        pipe = build_leader_pipeline(
            n_verify=1, n_bank=2, pool_size=N, gen_limit=N, batch=32,
            verify_precomputed=True,
            slot_clock=cfg if clocked else None,
        )
        try:
            if clocked:
                deadline = time.monotonic() + 30
                while (not pipe.poh.window_closed
                       and time.monotonic() < deadline):
                    for s in pipe.stages:
                        s.run_once()
                # drain the committed tail through shred/store
                pipe.finish()
            else:
                pipe.run(until_txns=N, max_iters=400_000)
            report = {
                "landed": sum(b.metrics.get("txn_exec")
                              for b in pipe.banks),
                "rejected": sum(b.metrics.get("txn_rejected")
                                for b in pipe.banks),
                "dropped": pipe.pack.metrics.get("txn_dropped"),
                "shed": pipe.pack.metrics.get("txn_shed"),
            }
            poh_m = pipe.poh.metrics
            stats = {
                "sealed": poh_m.get("slots_sealed"),
                "missed": poh_m.get("slot_missed"),
                "seal_p99_ns": poh_m.quantile("slot_seal_lag_ns", 0.99),
                "blocks_closed": pipe.pack.metrics.get("blocks_closed"),
            }
            return report, stats
        finally:
            pipe.close()

    clocked, cstats = run(clocked=True)
    # cadence: every slot sealed AT its deadline, jitter inside grace
    assert cstats["sealed"] == n_slots, cstats
    assert cstats["missed"] == 0, cstats
    grace_ns = cfg.miss_grace_frac * cfg.slot_ms * 1e6
    assert 0 < cstats["seal_p99_ns"] <= grace_ns, cstats
    assert cstats["blocks_closed"] >= 1, cstats  # tail carried >= once
    # zero loss under the clock
    assert clocked["dropped"] == 0 and clocked["shed"] == 0
    # regression diff vs the clock-off stream: same landed/rejected split
    free, _ = run(clocked=False)
    assert clocked["landed"] == free["landed"] == N
    assert clocked["rejected"] == free["rejected"] == 0
