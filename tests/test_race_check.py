"""race_check (fdlint FD4xx) tests: every rule fires on its seeded
fixture (tests/fixtures/race/) with an exact count, every clean control
stays silent, inline suppression works in both languages, the fused
poh+shred topology resolves to ONE crash domain, and — the tier-1
contract — the shipped repo diffs CLEAN inside the runtime budget.
"""

import os
import sys
import time
from collections import Counter

import pytest

from firedancer_tpu.analysis import race_check as rc
from firedancer_tpu.analysis import topo_check
from firedancer_tpu.analysis.framework import all_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures", "race")
RING_FIRE = os.path.join(FIX, "ring_fire.py")
RING_CLEAN = os.path.join(FIX, "ring_clean.py")
FENCE_FIRE = os.path.join(FIX, "fence_fire.cpp")
FENCE_CLEAN = os.path.join(FIX, "fence_clean.cpp")


@pytest.fixture()
def racefix_path():
    """Make the fixture topology package (racefix) importable."""
    sys.path.insert(0, FIX)
    try:
        yield
    finally:
        sys.path.remove(FIX)
        for mod in [m for m in sys.modules if m.split(".")[0] == "racefix"]:
            del sys.modules[mod]


# -- rule registry -----------------------------------------------------------


def test_fd4xx_rules_registered():
    ids = {r.id for r in all_rules()}
    for n in range(401, 407):
        assert f"FD{n}" in ids


# -- FD403/FD404/FD405: ring discipline fixtures -----------------------------


def test_ring_rules_fire_on_fixture():
    counts = Counter(f.rule for f in rc.check_ring_discipline([RING_FIRE]))
    assert counts == {
        "FD403": 1,  # LossyRelayStage discarded publish
        "FD404": 2,  # query read-back + raw mcache.table[] read-back
        "FD405": 1,  # speculative dcache copy, no re-check
    }, counts


def test_ring_findings_name_the_shape():
    by_rule = {}
    for f in rc.check_ring_discipline([RING_FIRE]):
        by_rule.setdefault(f.rule, f)
        assert f.path == RING_FIRE and f.line > 0
    assert "LossyRelayStage.during_frag" in by_rule["FD403"].msg
    assert "require_credit" in by_rule["FD403"].msg
    assert "prod.out.mcache" in by_rule["FD404"].msg
    assert "re-checks the seq" in by_rule["FD405"].msg


def test_ring_clean_controls_silent():
    findings = rc.check_ring_discipline([RING_CLEAN])
    assert findings == [], [f.format() for f in findings]


# -- FD406: native fence-discipline fixtures ---------------------------------


def _fence_findings():
    return rc.check_native(FIX)


def test_fd406_fires_on_fixture():
    fire = [f for f in _fence_findings() if f.path == FENCE_FIRE]
    assert len(fire) == 4, [f.format() for f in fire]
    assert all(f.rule == "FD406" for f in fire)
    msgs = " | ".join(f.msg for f in fire)
    assert "non-atomic" in msgs          # (a) bad_seq_read
    assert "memory_order_release" in msgs  # (b) bad_seq_store
    assert "torn payload" in msgs        # (c) bad_copy


def test_fd406_inline_disable_marks_suppressed():
    fire = [f for f in _fence_findings() if f.path == FENCE_FIRE]
    supp = [f for f in fire if f.suppressed]
    assert len(supp) == 1 and supp[0].suppressed == "inline"


def test_fd406_clean_control_silent():
    clean = [f for f in _fence_findings() if f.path == FENCE_CLEAN]
    assert clean == [], [f.format() for f in clean]


# -- FD401/FD402: crash-domain fixtures (the racefix mini topology) ----------


def test_fd401_fd402_fire_on_fixture_topology(racefix_path):
    findings = rc.check_cross_domain_state(["racefix.topo:build_fire"])
    counts = Counter(f.rule for f in findings)
    assert counts == {"FD401": 1, "FD402": 2}, \
        [f.format() for f in findings]
    fd401 = next(f for f in findings if f.rule == "FD401")
    assert fd401.path.endswith("shared.py")
    assert "'PENDING'" in fd401.msg and "relay_a" in fd401.msg \
        and "relay_b" in fd401.msg
    by_path = {os.path.basename(f.path) for f in findings
               if f.rule == "FD402"}
    assert by_path == {"stage_a.py", "sources.py"}
    src = next(f for f in findings if f.path.endswith("sources.py"))
    assert "resume_from_rings" in src.msg


def test_fd401_fd402_clean_topology_silent(racefix_path):
    findings = rc.check_cross_domain_state(["racefix.topo:build_clean"])
    assert findings == [], [f.format() for f in findings]


def test_domain_map_resolves_fixture_builders(racefix_path):
    topo = rc._resolve_topo("racefix.topo:build_fire")
    doms = {name: {c.__name__ for c in classes}
            for name, classes, _restartable in rc.domain_map(topo)}
    assert doms == {"gen": {"GenStage"},
                    "relay_a": {"RelayAStage"},
                    "relay_b": {"RelayBStage"}}


# -- inline suppression, Python side -----------------------------------------


def test_python_inline_disable_marks_suppressed(tmp_path):
    p = tmp_path / "lossy.py"
    p.write_text(
        "class S:\n"
        "    def after_frag(self, out_idx, sig, sz):\n"
        "        self.publish(0, b'x', sig=sig)"
        "  # fdlint: disable=FD403 -- lossy by design\n"
    )
    findings = rc.check_repo(paths=[str(p)], topo_specs=[],
                             native_dir=str(tmp_path))
    assert [f.rule for f in findings] == ["FD403"]
    assert findings[0].suppressed == "inline"


# -- the fused poh+shred crash domain (topo_check satellite) -----------------


def test_fused_topology_validates_and_drops_ps_link():
    from firedancer_tpu.models.leader_topo import build_leader_topology_fused

    topo = build_leader_topology_fused()
    topo_check.validate_or_raise(topo, label="fused")  # FD1xx green
    assert "ps" not in {ls.name for ls in topo.links}
    names = [s.name for s in topo.stages]
    assert "poh_shred" in names
    assert "poh" not in names and "shred" not in names


def test_fused_stage_is_one_restart_domain():
    from firedancer_tpu.models.leader_topo import (
        build_leader_topology, build_leader_topology_fused,
    )

    fused = dict(topo_check.restart_domains(build_leader_topology_fused()))
    assert "poh_shred" in fused  # ONE domain for both halves
    unfused = dict(topo_check.restart_domains(build_leader_topology()))
    assert "poh" in unfused and "shred" in unfused
    assert "poh_shred" not in unfused


def test_domain_map_resolves_fused_stage():
    topo = rc._resolve_topo(
        "firedancer_tpu.models.leader_topo:build_leader_topology_fused")
    doms = {name: {c.__name__ for c in classes}
            for name, classes, _restartable in rc.domain_map(topo)}
    assert doms["poh_shred"] == {"FusedPohShredStage"}


# -- the acceptance gate -----------------------------------------------------


def test_repo_diffs_clean_and_fast():
    """Zero unsuppressed FD4xx findings over the shipped tree, well
    inside the fdlint wall budget (the CLI gate test runs this once per
    suite via scripts/fdlint.sh; ISSUE 17 pins FD2xx+FD3xx+FD4xx under
    2 s — the 5 s ceiling here is slack for loaded CI hosts, matching
    test_abi_check's)."""
    t0 = time.monotonic()
    findings = rc.check_repo()
    dt = time.monotonic() - t0
    active = [f for f in findings if not f.suppressed]
    assert active == [], [f.format() for f in active]
    # the two waived repo findings stay VISIBLE as suppressed entries
    assert {(f.rule, f.suppressed) for f in findings} <= \
        {("FD401", "inline"), ("FD403", "inline")}
    assert dt < 5.0, f"race_check took {dt:.2f}s (budget 5s)"
