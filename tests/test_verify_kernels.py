"""Verify kernel ladder + async window + autotuner (ISSUE 13).

Tier-1 here is structural and host-only: ladder introspection (dispatch
counts), the >= 8 deep in-flight window's in-order/backpressure
semantics driven with fake device futures (no XLA), and the autotuner's
determinism.  The compile-heavy differential lanes (fused vs split vs
baseline masks on adversarial inputs, cached interleave) live behind
the `slow` marker — a single sigverify-program compile costs ~3 min on
one core.
"""

import hashlib

import numpy as np
import pytest

from firedancer_tpu.runtime import verify_tune as vt
from firedancer_tpu.runtime.benchg import gen_transfer_pool
from firedancer_tpu.runtime.verify import VerifyStage


# -- ladder structure (no device) ---------------------------------------------


def test_kernel_ladder_dispatch_counts():
    from firedancer_tpu.ops import sigverify as sv

    assert set(sv.KERNEL_LADDER) == {"fused", "baseline", "split"}
    assert sv.kernel_dispatch_count("fused") == 1
    assert sv.kernel_dispatch_count("baseline") == 1
    assert sv.kernel_dispatch_count("split") == 4
    with pytest.raises(KeyError):
        sv.kernel_dispatch_count("nope")


def test_stage_rejects_unknown_kernel():
    with pytest.raises(ValueError, match="unknown verify kernel"):
        VerifyStage("v", ins=[], outs=[], kernel="warp")


def test_stage_kernel_and_window_defaults():
    st = VerifyStage("v", ins=[], outs=[], native_client=False)
    assert st.kernel == "fused"
    assert st.max_inflight >= 8  # the wiredancer-grade window


# -- the async in-flight window (fake futures, no XLA) ------------------------


class _FakeResult:
    """A controllable device future: is_ready() flips on demand, and
    np.asarray() returns the prepared mask (the reap-point contract)."""

    def __init__(self, mask: np.ndarray):
        self.mask = mask
        self.ready = False

    def is_ready(self) -> bool:
        return self.ready

    def __array__(self, dtype=None, copy=None):
        return self.mask


class _WindowStage(VerifyStage):
    """VerifyStage with the device replaced by fake futures."""

    def __init__(self, *a, **kw):
        kw.setdefault("native_client", False)
        super().__init__(*a, **kw)
        self.fakes: list[_FakeResult] = []
        self.emitted: list = []

    def _dispatch(self, acc, cached):
        f = _FakeResult(np.ones((len(acc.elems),), dtype=bool))
        self.fakes.append(f)
        return f, None

    def _emit_burst(self, emits):
        self.emitted.extend(emits)
        if emits:
            self.metrics.inc("txn_verified", len(emits))


def _feed(st, pool, t0=1000):
    meta = np.zeros(7, dtype=np.uint64)
    for i, p in enumerate(pool):
        meta[5] = t0 + i
        st.after_frag(0, meta, p)


@pytest.fixture(scope="module")
def txn_pool():
    return gen_transfer_pool(48, n_payers=8, n_dests=64)


def test_window_fills_to_max_inflight_and_defers(txn_pool):
    st = _WindowStage("v", ins=[], outs=[], batch=4, max_msg_len=256,
                      max_inflight=8)
    _feed(st, txn_pool[:44])  # 11 batches of 4
    # nothing reaped (no fake is ready): the window holds exactly 8 and
    # the remaining sealed batches parked in the submit queue — submit
    # never blocked on a device future
    assert len(st._inflight) == 8
    assert len(st._submit_queue) == 3
    assert st.metrics.get("submit_deferred") > 0
    assert st.metrics.get("batches") == 8  # only submitted ones dispatched
    occ = st.metrics.hist("inflight_occupancy")
    assert occ["count"] == 8 and occ["sum"] > 0


def test_window_reaps_in_order_under_out_of_order_completion(txn_pool):
    st = _WindowStage("v", ins=[], outs=[], batch=4, max_msg_len=256,
                      max_inflight=8)
    _feed(st, txn_pool[:32])  # 8 batches
    assert len(st.fakes) == 8
    # complete LATER batches first: nothing may emit past the head
    for f in st.fakes[1:]:
        f.ready = True
    st.after_credit()
    assert st.emitted == []
    # head completes: everything reaps, in submission order
    st.fakes[0].ready = True
    st.after_credit()
    assert len(st.emitted) == 32
    tsorigs = [e[2] for e in st.emitted]
    assert tsorigs == sorted(tsorigs)  # global emit order = intake order


def test_window_freed_slots_pull_deferred_submits(txn_pool):
    st = _WindowStage("v", ins=[], outs=[], batch=4, max_msg_len=256,
                      max_inflight=3)
    _feed(st, txn_pool[:24])  # 6 batches: 3 in flight + 3 parked
    assert len(st._inflight) == 3 and len(st._submit_queue) == 3
    st.fakes[0].ready = True
    st.after_credit()
    # one reap -> one parked batch submitted into the freed slot
    assert len(st._inflight) == 3
    assert len(st._submit_queue) == 2
    assert len(st.fakes) == 4


def test_flush_drains_window_and_queue(txn_pool):
    st = _WindowStage("v", ins=[], outs=[], batch=4, max_msg_len=256,
                      max_inflight=3)
    _feed(st, txn_pool[:30])  # 7 full batches + a partial
    for f in st.fakes:
        f.ready = True
    # flush must close the partial, pump the queue, and reap everything
    # (fakes created during flush are ready=False but the blocking drain
    # materializes them via __array__ regardless — the jax contract)
    st.flush()
    assert len(st.emitted) == 30
    assert not st._inflight and not st._submit_queue


def test_deep_submit_queue_falls_back_to_blocking_drain(txn_pool):
    st = _WindowStage("v", ins=[], outs=[], batch=4, max_msg_len=256,
                      max_inflight=2)
    st._submit_queue_max = 2
    _feed(st, txn_pool[:40])  # 10 batches >> window + queue bound
    # the memory bound engaged: the blocking drain consumed heads, so
    # the queue never exceeds its bound + the one being closed
    assert len(st._submit_queue) <= st._submit_queue_max + 1
    assert len(st.emitted) > 0  # heads were reaped to make room


# -- autotuner ----------------------------------------------------------------


def _hist(values, buckets):
    """Build a Metrics-shaped histogram dict from raw observations."""
    from bisect import bisect_left

    counts = [0] * (len(buckets) + 1)
    for v in values:
        counts[bisect_left(buckets, v)] += 1
    return {"buckets": list(buckets), "counts": counts,
            "sum": float(sum(values)), "count": len(values)}


def test_autotune_recommend_deterministic():
    from firedancer_tpu.utils import metrics as fm

    fills = _hist([300, 310, 290, 305] * 8, fm.exp_buckets(1, 4096, 13))
    msgs = _hist([180, 200, 150] * 10, fm.exp_buckets(32, 2048, 13))
    a = vt.recommend(fills, msgs, batch_elems=1000, comb_elems=100)
    b = vt.recommend(fills, msgs, batch_elems=1000, comb_elems=100)
    assert a == b  # same histograms -> same geometry, always
    assert a.batch in vt.BATCH_LADDER
    assert a.max_msg_len in vt.MSG_LEN_LADDER
    # p95 fill ~512-bucket -> batch rung must cover it
    assert a.batch >= 300
    assert a.max_msg_len >= 200
    assert a.comb_split is False  # 10% comb share < the split threshold


def test_autotune_comb_split_threshold():
    assert vt.recommend({}, None, batch_elems=100,
                        comb_elems=50).comb_split is True
    assert vt.recommend({}, None, batch_elems=100,
                        comb_elems=10).comb_split is False
    # no evidence: keep the current choice
    cur = vt.Geometry(128, 256, False)
    assert vt.recommend({}, None, current=cur) == cur


def test_autotune_overflow_takes_top_rung():
    from firedancer_tpu.utils import metrics as fm

    buckets = fm.exp_buckets(1, 4096, 13)
    fills = _hist([5000] * 16, buckets)  # above the top edge
    rec = vt.recommend(fills, None, batch_elems=1, comb_elems=0)
    assert rec.batch == vt.BATCH_LADDER[-1]


def test_stage_autotune_applies_at_quiet_housekeeping(txn_pool):
    def run(stage):
        _feed(stage, txn_pool)
        stage.flush()
        stage.during_housekeeping()
        return stage.batch, stage.max_msg_len

    a = VerifyStage("a", ins=[], outs=[], batch=2048, max_msg_len=1232,
                    precomputed_ok=True, autotune_after=1,
                    native_client=False)
    b = VerifyStage("b", ins=[], outs=[], batch=2048, max_msg_len=1232,
                    precomputed_ok=True, autotune_after=1,
                    native_client=False)
    ga, gb = run(a), run(b)
    assert ga == gb  # deterministic per identical input stream
    # 48 txns of ~150-byte transfers against a 2048/1232 shape: the
    # evidence must shrink both axes
    assert ga[0] < 2048 and ga[1] < 1232
    assert a.metrics.get("retunes") == 1


def test_stage_autotune_waits_for_quiet_point(txn_pool):
    st = _WindowStage("v", ins=[], outs=[], batch=4, max_msg_len=1232,
                      max_inflight=8, autotune_after=1)
    _feed(st, txn_pool[:32])
    assert st._inflight  # batches outstanding
    st._maybe_retune()
    assert st.batch == 4  # never retunes with work in flight


# -- differential lanes (compile-heavy: slow tier) ----------------------------


MAX_MSG = 96


def _cases(rng):
    """Adversarial (msg, sig, pubkey) triples + expected mask."""
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    L = (1 << 252) + 27742317777372353535851937790883648493
    cases, expect = [], []
    for i in range(4):  # honest, varied lengths incl. empty
        secret = hashlib.sha256(b"k%d" % i).digest()
        pub = ref.public_key(secret)
        m = rng.bytes(int(rng.integers(0, MAX_MSG + 1)))
        cases.append((m, ref.sign(secret, m), pub))
        expect.append(True)
    secret = hashlib.sha256(b"adv").digest()
    pub = ref.public_key(secret)
    m = b"the quick brown fox"
    s = ref.sign(secret, m)
    # truncated message
    cases.append((m[:-1], s, pub))
    expect.append(False)
    # non-canonical s (s + L re-encoding of a valid sig)
    s_val = int.from_bytes(s[32:], "little")
    bad_s = s[:32] + (s_val + L).to_bytes(32, "little")
    cases.append((m, bad_s, pub))
    expect.append(False)
    # small-order A (torsion point: the identity, y=1)
    torsion = b"\x01" + b"\x00" * 31
    cases.append((m, s, torsion))
    expect.append(False)
    # small-order R
    bad_r = torsion + s[32:]
    cases.append((m, bad_r, pub))
    expect.append(False)
    # corrupted sig bits
    flip = bytearray(s)
    flip[2] ^= 4
    cases.append((m, bytes(flip), pub))
    expect.append(False)
    return cases, expect


def _arrays(cases):
    b = len(cases)
    msg = np.zeros((MAX_MSG, b), dtype=np.uint8)
    ln = np.zeros(b, dtype=np.int32)
    sig = np.zeros((64, b), dtype=np.uint8)
    pk = np.zeros((32, b), dtype=np.uint8)
    for i, (m, s, p) in enumerate(cases):
        msg[: len(m), i] = np.frombuffer(m, dtype=np.uint8)
        ln[i] = len(m)
        sig[:, i] = np.frombuffer(s, dtype=np.uint8)
        pk[:, i] = np.frombuffer(p, dtype=np.uint8)
    return msg, ln, sig, pk


@pytest.mark.slow  # three sigverify-program compiles (~3 min each)
def test_ladder_lanes_byte_identical_masks(rng):
    import jax.numpy as jnp

    from firedancer_tpu.ops import sigverify as sv

    cases, expect = _cases(rng)
    msg, ln, sig, pk = _arrays(cases)
    args = (jnp.asarray(msg), jnp.asarray(ln), jnp.asarray(sig),
            jnp.asarray(pk))
    n = len(cases)
    masks = {}
    for kernel in sv.KERNEL_LADDER:
        mask, n_ok = sv.verify_dispatch(kernel, *args, n,
                                        max_msg_len=MAX_MSG)
        masks[kernel] = np.asarray(mask)[:n]
        if n_ok is not None:
            assert int(np.asarray(n_ok)) == int(masks[kernel].sum())
    assert masks["fused"].tolist() == expect
    assert masks["fused"].tolist() == masks["baseline"].tolist()
    assert masks["fused"].tolist() == masks["split"].tolist()
    # the fused program masks pad lanes ON DEVICE
    mask, n_ok = sv.verify_dispatch("fused", *args, n - 2,
                                    max_msg_len=MAX_MSG)
    got = np.asarray(mask)
    assert not got[n - 2:].any()
    assert int(np.asarray(n_ok)) == int(got[: n - 2].sum())


@pytest.mark.slow  # fused + cached kernel compiles
def test_cached_lane_interleave_matches_generic(rng):
    """Cached-signer (comb) verifies agree with the generic fused lane
    on an interleaved honest/adversarial batch."""
    import jax.numpy as jnp

    from firedancer_tpu.ops import sigverify as sv
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    signers = [hashlib.sha256(b"c%d" % i).digest() for i in range(3)]
    pubs = [ref.public_key(s) for s in signers]
    cases = []
    for i in range(8):
        sec, pub = signers[i % 3], pubs[i % 3]
        m = rng.bytes(int(rng.integers(1, MAX_MSG)))
        s = ref.sign(sec, m)
        if i == 5:
            m = m[:-1] + b"\xff"  # one corrupted element mid-batch
        cases.append((m, s, pub))
    msg, ln, sig, pk = _arrays(cases)
    n = len(cases)
    gen_mask, _ = sv.verify_dispatch(
        "fused", jnp.asarray(msg), jnp.asarray(ln), jnp.asarray(sig),
        jnp.asarray(pk), n, max_msg_len=MAX_MSG)
    fill = np.zeros((32, len(pubs)), dtype=np.uint8)
    for i, p in enumerate(pubs):
        fill[:, i] = np.frombuffer(p, dtype=np.uint8)
    tables, ok = sv.comb_fill(jnp.asarray(fill))
    assert bool(np.asarray(ok).all())
    bank = sv.bank_alloc(len(pubs))
    bank = sv.bank_install(
        bank, tables, jnp.asarray(np.arange(len(pubs), dtype=np.int32)))
    slots = jnp.asarray(
        np.asarray([i % 3 for i in range(n)], dtype=np.int32))
    cached = sv.ed25519_verify_batch_cached(
        jnp.asarray(msg), jnp.asarray(ln), jnp.asarray(sig),
        jnp.asarray(pk), bank, slots, max_msg_len=MAX_MSG)
    assert np.asarray(cached)[:n].tolist() == \
        np.asarray(gen_mask)[:n].tolist()
