"""PersistentFunk: journal recovery, torn-tail truncation, compaction,
publish atomicity across restart."""

import os
import struct
import zlib

from firedancer_tpu.funk.persist import PersistentFunk, _FRAME_HDR, _MAGIC


def reopen(d):
    return PersistentFunk(str(d))


def test_restart_replays_journal(tmp_path):
    d = tmp_path / "db"
    with PersistentFunk(str(d)) as f:
        f.rec_insert(None, b"k1", b"v1")
        f.rec_insert(None, b"k2", b"v2")
        f.rec_remove(None, b"k1")
    with reopen(d) as f:
        assert f.rec_query(None, b"k1") is None
        assert f.rec_query(None, b"k2") == b"v2"
        assert f.recovered_frames == 3


def test_publish_is_one_frame_and_survives(tmp_path):
    d = tmp_path / "db"
    with PersistentFunk(str(d)) as f:
        f.rec_insert(None, b"base", b"0")
        x = f.txn_prepare(None, b"x1")
        f.rec_insert(x, b"a", b"1")
        f.rec_insert(x, b"b", b"2")
        f.rec_remove(x, b"base")
        frames_before = f.recovered_frames  # 0 on first open
        f.txn_publish(x)
        assert frames_before == 0
    with reopen(d) as f:
        # the publish is 1 frame (plus the base insert)
        assert f.recovered_frames == 2
        assert f.rec_query(None, b"a") == b"1"
        assert f.rec_query(None, b"b") == b"2"
        assert f.rec_query(None, b"base") is None


def test_torn_tail_truncated(tmp_path):
    d = tmp_path / "db"
    with PersistentFunk(str(d)) as f:
        f.rec_insert(None, b"good", b"yes")
    wal = os.path.join(str(d), "funk.wal")
    with open(wal, "ab") as fh:
        # half a frame: valid header, truncated payload
        fh.write(_FRAME_HDR.pack(100, zlib.crc32(b"x")))
        fh.write(b"partial")
    with reopen(d) as f:
        assert f.rec_query(None, b"good") == b"yes"
        assert f.recovered_frames == 1
    # tail was truncated: the journal ends exactly after the good frame
    with reopen(d) as f:
        assert f.recovered_frames == 1


def test_corrupt_crc_stops_replay(tmp_path):
    d = tmp_path / "db"
    with PersistentFunk(str(d)) as f:
        f.rec_insert(None, b"k1", b"v1")
        f.rec_insert(None, b"k2", b"v2")
    wal = os.path.join(str(d), "funk.wal")
    blob = bytearray(open(wal, "rb").read())
    blob[-1] ^= 0xFF  # corrupt the LAST frame's payload
    open(wal, "wb").write(bytes(blob))
    with reopen(d) as f:
        assert f.rec_query(None, b"k1") == b"v1"
        assert f.rec_query(None, b"k2") is None  # dropped with the bad frame


def test_compaction_resets_journal_and_preserves_state(tmp_path):
    d = tmp_path / "db"
    with PersistentFunk(str(d), min_compact_bytes=2048) as f:
        for i in range(200):
            f.rec_insert(None, b"key%03d" % (i % 10), os.urandom(64))
        # journal far exceeds 10 live keys x 64B -> compaction happened
        assert os.path.getsize(os.path.join(str(d), "funk.wal")) < 64 * 200
        assert os.path.exists(os.path.join(str(d), "funk.snap"))
        live = {k: f.rec_query(None, k) for k in f.rec_keys(None)}
        assert len(live) == 10
    with reopen(d) as f:
        for k, v in live.items():
            assert f.rec_query(None, k) == v


def test_explicit_compact_then_more_writes(tmp_path):
    d = tmp_path / "db"
    with PersistentFunk(str(d)) as f:
        f.rec_insert(None, b"a", b"1")
        f.compact()
        f.rec_insert(None, b"b", b"2")
    with reopen(d) as f:
        assert f.rec_query(None, b"a") == b"1"
        assert f.rec_query(None, b"b") == b"2"
        assert f.recovered_frames == 1  # only the post-compact write


def test_empty_dir_starts_clean(tmp_path):
    with PersistentFunk(str(tmp_path / "fresh")) as f:
        assert f.rec_cnt_root() == 0
        assert f.recovered_frames == 0


def test_fork_semantics_untouched(tmp_path):
    """The fork tree still behaves exactly like in-memory Funk."""
    with PersistentFunk(str(tmp_path / "db")) as f:
        a = f.txn_prepare(None, b"a")
        b = f.txn_prepare(a, b"b")
        f.rec_insert(b, b"k", b"deep")
        c = f.txn_prepare(None, b"c")  # competing fork
        f.rec_insert(c, b"k", b"loser")
        f.txn_publish(b)
        assert f.rec_query(None, b"k") == b"deep"
        assert f.txn_cnt() == 0  # competitor cancelled


def test_funk_from_config(tmp_path):
    from firedancer_tpu.funk.persist import funk_from_config
    from firedancer_tpu.utils.config import Config

    cfg = Config()
    f = funk_from_config(cfg)
    # no funk_dir -> the in-memory store via the make_funk funnel: the
    # native shm map when the lane is up, the dict store otherwise
    assert type(f).__name__ in ("Funk", "NativeFunk")
    cfg.ledger.funk_dir = str(tmp_path / "db")
    with funk_from_config(cfg) as f2:
        f2.rec_insert(None, b"k", b"v")
    with funk_from_config(cfg) as f3:
        assert f3.rec_query(None, b"k") == b"v"


def test_garbage_magic_truncates_whole_wal(tmp_path):
    """A WAL whose magic header is torn/garbage must be truncated to
    zero — otherwise new frames append AFTER the garbage and every later
    recovery silently drops them all (r4 advisor finding)."""
    d = tmp_path / "db"
    os.makedirs(str(d), exist_ok=True)
    with open(os.path.join(str(d), "funk.wal"), "wb") as fh:
        fh.write(b"NOTMAGIC" + b"\xde\xad\xbe\xef" * 8)
    with PersistentFunk(str(d)) as f:
        assert f.recovered_frames == 0
        f.rec_insert(None, b"after", b"garbage")
    # the batch written after recovery MUST survive the next restart
    with reopen(d) as f:
        assert f.rec_query(None, b"after") == b"garbage"
        assert f.recovered_frames == 1
