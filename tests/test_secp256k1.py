"""secp256k1 recover tests: curve sanity against public constants,
sign->recover round trips, malleability/low-s, invalid-input rejection,
Ethereum address derivation."""

import hashlib

import pytest

from firedancer_tpu.ops import secp256k1 as sk


def test_generator_on_curve_and_order():
    assert (sk.GY * sk.GY - (sk.GX**3 + 7)) % sk.P == 0
    assert sk._mul(sk.N, sk.G) is None  # n*G = infinity
    # 2G's x is a public constant
    two_g = sk._mul(2, sk.G)
    assert two_g[0] == 0xC6047F9441ED7D6D3045406E95C07CD85C778E4B8CEF3CA7ABAC09B95C709EE5


def test_sign_recover_roundtrip():
    for i in range(1, 6):
        secret = int.from_bytes(hashlib.sha256(b"k%d" % i).digest(), "big") % sk.N
        pub = sk.pubkey_of(secret)
        pub64 = pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")
        h = hashlib.sha256(b"msg%d" % i).digest()
        sig, rec = sk.sign(secret, h)
        assert sk.recover(h, rec, sig) == pub64
        assert sk.verify(h, sig, pub64)
        # wrong recovery id yields a DIFFERENT key (or an error), never ours
        try:
            other = sk.recover(h, rec ^ 1, sig)
            assert other != pub64
        except sk.RecoverError:
            pass


def test_low_s_canonical():
    secret = 12345
    h = hashlib.sha256(b"low-s").digest()
    sig, _ = sk.sign(secret, h)
    s = int.from_bytes(sig[32:], "big")
    assert s <= sk.N // 2


def test_recover_rejects_invalid():
    h = hashlib.sha256(b"x").digest()
    with pytest.raises(sk.RecoverError):
        sk.recover(h, 5, b"\x01" * 64)  # bad id
    with pytest.raises(sk.RecoverError):
        sk.recover(h, 0, b"\x00" * 64)  # r = s = 0
    with pytest.raises(sk.RecoverError):
        sk.recover(h[:-1], 0, b"\x01" * 64)  # short hash
    # r = N (out of scalar range)
    bad = sk.N.to_bytes(32, "big") + (1).to_bytes(32, "big")
    with pytest.raises(sk.RecoverError):
        sk.recover(h, 0, bad)


def test_tampered_message_recovers_different_key():
    secret = 999
    pub = sk.pubkey_of(secret)
    pub64 = pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")
    h = hashlib.sha256(b"honest").digest()
    sig, rec = sk.sign(secret, h)
    h2 = hashlib.sha256(b"forged").digest()
    try:
        assert sk.recover(h2, rec, sig) != pub64
    except sk.RecoverError:
        pass


def test_eth_address():
    # address of privkey 1's pubkey is a public constant
    pub = sk.pubkey_of(1)
    pub64 = pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")
    assert sk.eth_address(pub64).hex() == "7e5f4552091a69125d5dfcb7b8c2659029395bdf"
