"""Differential suite for the native shm funk store (ISSUE 19,
native/fd_funk.cpp + funk/funk_native.py).

Lane parity is the contract: the dict-backed `funk/funk.py` store and
the shm-backed `NativeFunk` must agree op-for-op — fork-tree
prepare/publish/cancel with sibling cancellation, overlay queries,
tombstones, frozen-txn protection, FunkError codes — and the runtime
paths built on top (execute_block's staged-ancestor duplicate gate,
snapshot round-trip, the cluster partition-heal replay) must produce
byte-identical bank hashes whichever lane `make_funk()` picks.

The module SKIPS (never fails) without the toolchain or with
FDTPU_NATIVE_FUNK=0.
"""

from __future__ import annotations

import os
import random

import pytest

from firedancer_tpu.funk import ERR_FROZEN, ERR_KEY, ERR_TXN, Funk, FunkError
from firedancer_tpu.funk import funk_native as fn

if not fn.available():
    pytest.skip(
        "native funk unavailable (no toolchain or FDTPU_NATIVE_FUNK=0)",
        allow_module_level=True,
    )


def _pair() -> tuple[Funk, fn.NativeFunk]:
    return Funk(), fn.NativeFunk()


def _root_state(f) -> dict[bytes, bytes]:
    return {k: f.rec_query(None, k) for k in f.rec_keys(None)}


def _close(nf) -> None:
    nf.close()


# -- op-for-op randomized streams --------------------------------------------


def _apply(f, op: str, a: tuple):
    """One op against one lane; (\"ok\", result) or (\"err\", code) so the
    two lanes' outcomes compare as plain values."""
    try:
        if op == "prepare":
            return ("ok", f.txn_prepare(a[0], a[1]))
        if op == "cancel":
            return ("ok", f.txn_cancel(a[0]))
        if op == "publish":
            return ("ok", f.txn_publish(a[0]))
        if op == "insert":
            return ("ok", f.rec_insert(a[0], a[1], a[2]))
        if op == "remove":
            return ("ok", f.rec_remove(a[0], a[1]))
        if op == "query":
            return ("ok", f.rec_query(a[0], a[1]))
        if op == "keys":
            return ("ok", sorted(f.rec_keys(a[0])))
        if op == "frozen":
            return ("ok", f.txn_is_frozen(a[0]))
        if op == "ancestry":
            return ("ok", f.txn_ancestry(a[0]))
        raise AssertionError(op)
    except FunkError as e:
        return ("err", e.code)


@pytest.mark.parametrize("seed", [1, 7, 1337])
def test_randomized_stream_parity(seed):
    """A seeded random op stream — including deliberately-invalid xids
    and keys — through both lanes; every return value and every
    FunkError code must match, and so must the final root state, txn
    count, and last_publish."""
    rng = random.Random(seed)
    py, nat = _pair()
    try:
        keys = [b"k%02d" % i for i in range(8)]
        xid_seq = 0
        live: list[bytes] = []  # xids we BELIEVE are live (may be stale
        # after a publish cancels siblings — that staleness is the test)

        for step in range(400):
            roll = rng.random()
            xid = rng.choice(live) if live and rng.random() < 0.9 \
                else b"ghost%d" % rng.randrange(4)
            if roll < 0.15:
                xid_seq += 1
                new = b"x%04d" % xid_seq
                parent = None if not live or rng.random() < 0.4 \
                    else rng.choice(live)
                op, a = "prepare", (parent, new)
                live.append(new)
            elif roll < 0.20:
                op, a = "cancel", (xid,)
            elif roll < 0.25:
                op, a = "publish", (xid,)
            elif roll < 0.50:
                tx = None if rng.random() < 0.3 else xid
                op, a = "insert", (tx, rng.choice(keys),
                                   b"v%d.%d" % (seed, step))
            elif roll < 0.60:
                tx = None if rng.random() < 0.3 else xid
                op, a = "remove", (tx, rng.choice(keys))
            elif roll < 0.80:
                tx = None if rng.random() < 0.3 else xid
                op, a = "query", (tx, rng.choice(keys))
            elif roll < 0.90:
                op, a = "keys", (None if rng.random() < 0.5 else xid,)
            elif roll < 0.95:
                op, a = "frozen", (xid,)
            else:
                op, a = "ancestry", (xid,)

            rp = _apply(py, op, a)
            rn = _apply(nat, op, a)
            assert rp == rn, (
                f"step {step}: {op}{a!r} diverged: py={rp} native={rn}")
            # prune the live list on success so it tracks reality-ish
            # (publish cancels competing siblings, cancel kills subtrees)
            if op in ("cancel", "publish") and rp[0] == "ok":
                live = [x for x in live
                        if _apply(py, "ancestry", (x,))[0] == "ok"]

        assert _root_state(py) == _root_state(nat)
        assert py.txn_cnt() == nat.txn_cnt()
        assert py.last_publish == nat.last_publish
        assert py.rec_cnt_root() == nat.rec_cnt_root()
    finally:
        _close(nat)


# -- targeted fork semantics --------------------------------------------------


def test_publish_cancels_competing_siblings_both_lanes():
    py, nat = _pair()
    try:
        for f in (py, nat):
            f.rec_insert(None, b"acct", b"root-v")
            f.txn_prepare(None, b"A")
            f.txn_prepare(None, b"B")  # competing fork off root
            f.txn_prepare(b"A", b"A2")
            f.rec_insert(b"A2", b"acct", b"a2-v")
            f.rec_insert(b"B", b"acct", b"b-v")
            n = f.txn_publish(b"A2")
            assert n == 2  # A then A2
        for f in (py, nat):
            assert f.rec_query(None, b"acct") == b"a2-v"
            assert f.txn_cnt() == 0  # B cancelled with its ancestor's
            assert f.last_publish == b"A2"
            with pytest.raises(FunkError) as e:
                f.rec_insert(b"B", b"acct", b"late")
            assert e.value.code == ERR_TXN
        assert _root_state(py) == _root_state(nat)
    finally:
        _close(nat)


def test_sibling_overlay_isolation_both_lanes():
    py, nat = _pair()
    try:
        for f in (py, nat):
            f.rec_insert(None, b"k", b"root")
            f.txn_prepare(None, b"L")
            f.txn_prepare(None, b"R")
            f.rec_insert(b"L", b"k", b"left")
            assert f.rec_query(b"L", b"k") == b"left"
            assert f.rec_query(b"R", b"k") == b"root"  # sibling blind
            assert f.rec_query(None, b"k") == b"root"
    finally:
        _close(nat)


def test_tombstone_and_error_codes_both_lanes():
    py, nat = _pair()
    try:
        for f in (py, nat):
            with pytest.raises(FunkError) as e:
                f.rec_remove(None, b"absent")
            assert e.value.code == ERR_KEY
            f.rec_insert(None, b"k", b"v")
            f.txn_prepare(None, b"T")
            f.rec_remove(b"T", b"k")  # tombstone hides root from T
            assert f.rec_query(b"T", b"k") is None
            assert f.rec_query(None, b"k") == b"v"
            with pytest.raises(FunkError) as e:
                f.rec_remove(b"T", b"k")  # already dead as seen from T
            assert e.value.code == ERR_KEY
            f.txn_publish(b"T")
            assert f.rec_query(None, b"k") is None
            with pytest.raises(FunkError) as e:
                f.txn_publish(b"T")  # gone
            assert e.value.code == ERR_TXN
        assert _root_state(py) == _root_state(nat)
    finally:
        _close(nat)


def test_frozen_txn_and_recs_proxy_both_lanes():
    py, nat = _pair()
    try:
        for f in (py, nat):
            f.txn_prepare(None, b"P")
            recs = f.txn_recs_for_write(b"P")
            recs[b"a"] = b"1"
            recs.update([(b"b", b"2")])
            assert f.rec_query(b"P", b"a") == b"1"
            assert f.rec_query(b"P", b"b") == b"2"
            f.txn_prepare(b"P", b"C")
            assert f.txn_is_frozen(b"P")
            with pytest.raises(FunkError) as e:
                f.rec_insert(b"P", b"a", b"3")
            assert e.value.code == ERR_FROZEN
            with pytest.raises(FunkError) as e:
                f.txn_recs_for_write(b"P")
            assert e.value.code == ERR_FROZEN
            assert f.txn_ancestry(b"C") == [b"P", b"C"]
    finally:
        _close(nat)


def test_batch_apply_matches_per_record():
    """rec_insert_batch (one crossing, None = tombstone) lands the same
    state as the per-record Python path."""
    py, nat = _pair()
    try:
        py.rec_insert(None, b"dead", b"x")
        nat.rec_insert(None, b"dead", b"x")
        items = [(b"k%d" % i, b"v%d" % i) for i in range(32)]
        for k, v in items:
            py.rec_insert(None, k, v)
        py.rec_remove(None, b"dead")
        nat.rec_insert_batch(None, items + [(b"dead", None)])
        assert _root_state(py) == _root_state(nat)

        # and inside an overlay txn
        for f in (py, nat):
            f.txn_prepare(None, b"T")
        for k, v in items[:4]:
            py.rec_insert(b"T", k, v + b"'")
        nat.rec_insert_batch(b"T", [(k, v + b"'") for k, v in items[:4]])
        for k, v in items[:4]:
            assert py.rec_query(b"T", k) == nat.rec_query(b"T", k)
        for f in (py, nat):
            f.txn_publish(b"T")
        assert _root_state(py) == _root_state(nat)
    finally:
        _close(nat)


def test_txn_diff_reports_before_after():
    """The seal path's one-crossing read-out: before = the parent view
    at start of slot, after = the overlay's value (None = tombstone)."""
    py, nat = _pair()
    try:
        for f in (py, nat):
            f.rec_insert(None, b"mod", b"old")
            f.rec_insert(None, b"del", b"doomed")
            f.txn_prepare(None, b"S")
            f.rec_insert(b"S", b"mod", b"new")
            f.rec_insert(b"S", b"fresh", b"born")
            f.rec_remove(b"S", b"del")
        diff = {k: (b, a) for k, b, a in nat.txn_diff(b"S")}
        # the python lane has no txn_diff; the expectation is computed
        # from its public query surface (parent view vs overlay view)
        expect = {}
        for key in (b"mod", b"fresh", b"del"):
            expect[key] = (py.rec_query(None, key), py.rec_query(b"S", key))
        assert diff == expect
        assert diff[b"mod"] == (b"old", b"new")
        assert diff[b"fresh"] == (None, b"born")
        assert diff[b"del"] == (b"doomed", None)
    finally:
        _close(nat)


# -- runtime integration: the gate, the hash, the snapshot, the cluster ------


def _run_staged_gate(funk):
    from firedancer_tpu.flamenco.blockstore import StatusCache
    from firedancer_tpu.flamenco.runtime import acct_build, execute_block
    from firedancer_tpu.runtime.benchg import (
        gen_transfer_pool,
        pool_blockhash,
        pool_payers,
    )

    seed = b"funk-lane-gate"
    for _sec, pub in pool_payers(seed):
        funk.rec_insert(None, pub, acct_build(10**12))
    sc = StatusCache()
    sc.register_blockhash(pool_blockhash(seed), 0)
    txns = [bytes(p) for p in gen_transfer_pool(4, seed=seed)]
    r1 = execute_block(funk, slot=1, txns=txns, status_cache=sc,
                       ancestors={0})
    # same txns in a CHILD block while slot 1 is staged: gated
    r2 = execute_block(funk, slot=2, txns=txns,
                       parent_bank_hash=r1.bank_hash, parent_xid=r1.xid,
                       status_cache=sc, ancestors={0, 1})
    # a SIBLING fork off root is NOT gated by slot 1's staged entries
    r3 = execute_block(funk, slot=2, txns=txns, status_cache=sc,
                       ancestors={0})
    return (r1.bank_hash, r1.signature_cnt, r2.bank_hash,
            r2.signature_cnt, r3.bank_hash, r3.signature_cnt)


def test_staged_ancestor_gate_and_bank_hash_parity():
    """execute_block's exactly-once gate across staged (unrooted)
    ancestors behaves identically on both lanes, down to the bank
    hashes — the cluster/replay correctness bar."""
    py, nat = _pair()
    try:
        got_py = _run_staged_gate(py)
        got_nat = _run_staged_gate(nat)
        assert got_py == got_nat
        assert got_py[1] == 4  # slot 1 landed everything
        assert got_py[3] == 0  # staged ancestor gated the replay
        assert got_py[5] == 4  # sibling fork isolation held
    finally:
        _close(nat)


def test_snapshot_round_trip_across_lanes(tmp_path):
    """A snapshot written from the native store restores into BOTH
    lanes with identical root state (and vice versa)."""
    from firedancer_tpu.flamenco.runtime import acct_build
    from firedancer_tpu.flamenco.snapshot import snapshot_load, snapshot_write

    py, nat = _pair()
    try:
        recs = [(os.urandom(32), acct_build(1000 + i)) for i in range(16)]
        for k, v in recs:
            py.rec_insert(None, k, v)
        nat.rec_insert_batch(None, recs)

        p_nat = str(tmp_path / "nat.tar.zst")
        p_py = str(tmp_path / "py.tar.zst")
        n1 = snapshot_write(nat, p_nat, slot=5, bank_hash=b"\x05" * 32)
        n2 = snapshot_write(py, p_py, slot=5, bank_hash=b"\x05" * 32)
        assert n1 == n2 == 16
        # the archives carry the same accounts regardless of source lane
        back_py, man1 = snapshot_load(p_nat, Funk())
        back_nat, man2 = snapshot_load(p_py, fn.NativeFunk())
        try:
            assert man1.slot == man2.slot == 5
            assert _root_state(back_py) == _root_state(nat) == dict(recs)
            assert _root_state(back_nat) == _root_state(py) == dict(recs)
        finally:
            _close(back_nat)
    finally:
        _close(nat)


def test_readonly_attach_sees_live_store():
    """The read-replica shape: a second handle attached by shm name
    observes writes made through the owner (seqlock-consistent view)."""
    nat = fn.NativeFunk()
    try:
        ro = fn.NativeFunk.attach_readonly(nat.shm_name)
        try:
            nat.rec_insert(None, b"k", b"v1")
            assert ro.rec_query(None, b"k") == b"v1"
            nat.rec_insert(None, b"k", b"v2")
            assert ro.rec_query(None, b"k") == b"v2"
            assert ro.rec_cnt_root() == 1
        finally:
            _close(ro)
    finally:
        _close(nat)


@pytest.mark.slow
def test_cluster_partition_heal_replay_lane_parity(monkeypatch):
    """The partition-heal scenario — forks grow, the losing fork is
    pruned, state replays — summarizes byte-identically whichever lane
    make_funk() hands the validators."""
    from firedancer_tpu.chaos import scenario as cs

    monkeypatch.setenv(fn.ENV_SWITCH, "1")
    r_on = cs.run_scenario("partition-heal", seed=7)
    monkeypatch.setenv(fn.ENV_SWITCH, "0")
    r_off = cs.run_scenario("partition-heal", seed=7)
    assert r_on.ok, r_on.suite.describe()
    assert r_off.ok, r_off.suite.describe()
    assert r_on.to_json() == r_off.to_json()
