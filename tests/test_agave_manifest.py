"""Real Agave bank-manifest bincode + genuine-snapshot cold boot.

The manifest layout is fixed by the Solana snapshot protocol
(reference schema: src/flamenco/types/fd_types.json `solana_manifest`);
these tests exercise the full codec round-trip, the underflow-tolerant
trailing fields older snapshots omit, and an end-to-end cold boot from
an Agave-format archive into a funk the runtime can execute on."""

import hashlib

from firedancer_tpu.flamenco import agave_manifest as am
from firedancer_tpu.flamenco.appendvec import StoredAccount, write_appendvec
from firedancer_tpu.flamenco.runtime import acct_lamports
from firedancer_tpu.flamenco.snapshot import (
    agave_snapshot_load,
    agave_snapshot_write,
)
from firedancer_tpu.funk import Funk


def _h(tag: str) -> bytes:
    return hashlib.sha256(tag.encode()).digest()


def _rich_manifest() -> am.SolanaManifest:
    vote_acct = am.SolanaAccount(
        lamports=10_000_000, data=b"\x02" + b"\x00" * 99,
        owner=_h("vote-owner"), executable=False, rent_epoch=361,
    )
    stakes = am.Stakes(
        vote_accounts=[am.VoteAccountsPair(_h("vote1"), 5_000_000, vote_acct)],
        stake_delegations=[
            am.DelegationPair(
                _h("stake1"),
                am.Delegation(voter_pubkey=_h("vote1"), stake=5_000_000,
                              activation_epoch=100),
            )
        ],
        unused=0,
        epoch=250,
        stake_history=[
            am.StakeHistoryEntry(249, 5_000_000, 100, 50),
            am.StakeHistoryEntry(248, 4_900_000, 200, 0),
        ],
    )
    bank = am.VersionedBank(
        blockhash_queue=am.BlockhashQueue(
            last_hash_index=42,
            last_hash=_h("lasthash"),
            ages=[am.HashAgePair(_h("bh1"),
                                 am.HashAge(am.FeeCalculator(5000), 41, 7))],
            max_age=300,
        ),
        ancestors=[am.SlotPair(999, 0), am.SlotPair(998, 1)],
        hash=_h("bank"),
        parent_hash=_h("parent"),
        parent_slot=999,
        hard_forks=am.HardForks([am.SlotPair(500, 1)]),
        transaction_count=1_234_567,
        signature_count=999,
        capitalization=500_000_000_000,
        slot=1000,
        epoch=250,
        block_height=980,
        collector_id=_h("collector"),
        stakes=stakes,
        epoch_stakes=[
            am.EpochEpochStakesPair(
                250,
                am.EpochStakes(
                    stakes=stakes,
                    total_stake=5_000_000,
                    node_id_to_vote_accounts=[
                        am.PubkeyNodeVoteAccountsPair(
                            _h("node1"),
                            am.NodeVoteAccounts([_h("vote1")], 5_000_000),
                        )
                    ],
                    epoch_authorized_voters=[
                        am.PubkeyPubkeyPair(_h("vote1"), _h("authvoter"))
                    ],
                ),
            )
        ],
        is_delta=False,
    )
    return am.SolanaManifest(
        bank=bank,
        accounts_db=am.AccountsDbFields(
            storages=[
                am.SnapshotSlotAccVecs(998, [am.SnapshotAccVec(3, 0)]),
                am.SnapshotSlotAccVecs(1000, [am.SnapshotAccVec(7, 0)]),
            ],
            version=1,
            slot=1000,
            bank_hash_info=am.BankHashInfo(
                hash=_h("bh-info"), snapshot_hash=_h("snap-hash"),
                stats=am.BankHashStats(10, 1, 500_000_000_000, 4096, 2),
            ),
            historical_roots=[990, 991],
            historical_roots_with_hash=[am.SlotMapPair(989, _h("hr"))],
        ),
        lamports_per_signature=5000,
        bank_incremental_snapshot_persistence=(
            am.BankIncrementalSnapshotPersistence(
                900, _h("full"), 499_000_000_000, _h("inc"), 1_000_000_000
            )
        ),
        epoch_account_hash=_h("eah"),
        versioned_epoch_stakes=[
            (251, ("Current", am.EpochStakes(stakes=stakes,
                                             total_stake=5_000_000)))
        ],
    )


def test_manifest_roundtrip():
    m = _rich_manifest()
    blob = am.manifest_encode(m)
    m2 = am.manifest_decode(blob)
    assert m2 == m


def test_manifest_underflow_tolerant_tail():
    """Older manifests end right after lamports_per_signature — the
    trailing optional fields must decode as absent, not raise."""
    m = _rich_manifest()
    m.bank_incremental_snapshot_persistence = None
    m.epoch_account_hash = None
    m.versioned_epoch_stakes = []
    blob = am.manifest_encode(m)
    # strip the encoded empty tail: option(0) + option(0) + u64(0)
    stripped = blob[: len(blob) - (1 + 1 + 8)]
    m2 = am.manifest_decode(stripped)
    assert m2.bank == m.bank
    assert m2.bank_incremental_snapshot_persistence is None
    assert m2.epoch_account_hash is None
    assert m2.versioned_epoch_stakes == []


def test_manifest_rejects_trailing_garbage():
    m = _rich_manifest()
    blob = am.manifest_encode(m) + b"\x99"
    try:
        am.manifest_decode(blob)
    except Exception:
        pass
    else:
        raise AssertionError("trailing garbage accepted")


def _sa(tag, lamports, *, wv=0, data=b"", owner=None):
    return StoredAccount(
        pubkey=_h(tag), lamports=lamports,
        owner=owner or _h("system"), executable=False, rent_epoch=0,
        data=data, write_version=wv,
    )


def test_cold_boot_from_agave_archive(tmp_path):
    """Accounts restore newest-slot-wins with zero-lamport tombstones,
    straight into a funk root."""
    vec_old = write_appendvec([
        _sa("alice", 111, wv=1),
        _sa("bob", 222, wv=2),
        _sa("carol", 333, wv=3, data=b"hello"),
    ])
    vec_new = write_appendvec([
        _sa("alice", 999, wv=9),   # newer slot wins
        _sa("bob", 0, wv=10),      # tombstone: bob deleted at slot 1000
    ])
    m = _rich_manifest()
    m.accounts_db.storages = [
        am.SnapshotSlotAccVecs(998, [am.SnapshotAccVec(3, len(vec_old))]),
        am.SnapshotSlotAccVecs(1000, [am.SnapshotAccVec(7, len(vec_new))]),
    ]
    path = str(tmp_path / "snapshot-1000.tar.zst")
    agave_snapshot_write(path, m, {(998, 3): vec_old, (1000, 7): vec_new})

    funk, m2, summary = agave_snapshot_load(path)
    assert summary["slot"] == 1000
    assert summary["bank_hash"] == _h("bank")
    assert summary["accounts"] == 2  # alice + carol (bob tombstoned)
    assert summary["vote_accounts"] == 1
    assert summary["stake_delegations"] == 1
    assert acct_lamports(funk.rec_query(None, _h("alice"))) == 999
    assert funk.rec_query(None, _h("bob")) is None
    assert acct_lamports(funk.rec_query(None, _h("carol"))) == 333
    assert m2.bank.slot == 1000


def test_archive_with_status_cache_member_loads(tmp_path):
    """Genuine archives carry snapshots/status_cache next to the bank
    manifest; the loader must skip it, not decode it as a manifest."""
    import io
    import tarfile

    from firedancer_tpu.flamenco import snapshot as snap

    vec = write_appendvec([_sa("alice", 5, wv=1)])
    m = _rich_manifest()
    m.accounts_db.storages = [
        am.SnapshotSlotAccVecs(1000, [am.SnapshotAccVec(0, len(vec))]),
    ]
    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w") as tar:
        def add(name, payload):
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))

        add("version", b"1.2.0")
        add("snapshots/status_cache", b"\xde\xad\xbe\xef" * 10)
        add("snapshots/1000/1000", am.manifest_encode(m))
        add("accounts/1000.0", vec)
    path = str(tmp_path / "with_sc.tar.zst")
    with open(path, "wb") as f:
        # module codec shim: zstd where available, gzip fallback elsewhere
        f.write(snap._compress(tar_buf.getvalue(), 3))
    funk, m2, summary = agave_snapshot_load(path)
    assert summary["accounts"] == 1
    assert m2.bank.slot == 1000


def test_overlay_restore_tombstones_remove(tmp_path):
    """Loading an incremental onto a pre-populated funk must DELETE
    tombstoned accounts, not resurrect the base value."""
    base_vec = write_appendvec([_sa("gone", 100, wv=1), _sa("kept", 7, wv=2)])
    m1 = _rich_manifest()
    m1.accounts_db.storages = [
        am.SnapshotSlotAccVecs(900, [am.SnapshotAccVec(0, len(base_vec))]),
    ]
    p1 = str(tmp_path / "full.tar.zst")
    agave_snapshot_write(p1, m1, {(900, 0): base_vec})
    funk, _m, _s = agave_snapshot_load(p1)
    assert acct_lamports(funk.rec_query(None, _h("gone"))) == 100

    inc_vec = write_appendvec([_sa("gone", 0, wv=3)])  # deleted since base
    m2 = _rich_manifest()
    m2.accounts_db.storages = [
        am.SnapshotSlotAccVecs(1000, [am.SnapshotAccVec(0, len(inc_vec))]),
    ]
    p2 = str(tmp_path / "inc.tar.zst")
    agave_snapshot_write(p2, m2, {(1000, 0): inc_vec})
    agave_snapshot_load(p2, funk=funk)
    assert funk.rec_query(None, _h("gone")) is None
    assert acct_lamports(funk.rec_query(None, _h("kept"))) == 7


def test_restored_funk_executes_blocks(tmp_path):
    """The booted state is live: a transfer block executes on it."""
    from firedancer_tpu.flamenco.runtime import TXN_SUCCESS, execute_block
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.protocol import txn as ft

    secret = hashlib.sha256(b"snap-payer").digest()
    payer = ref.public_key(secret)
    vec = write_appendvec([
        StoredAccount(pubkey=payer, lamports=10**9,
                      owner=ft.SYSTEM_PROGRAM,
                      executable=False, rent_epoch=0, data=b"",
                      write_version=1),
    ])
    m = _rich_manifest()
    m.accounts_db.storages = [
        am.SnapshotSlotAccVecs(1000, [am.SnapshotAccVec(0, len(vec))]),
    ]
    path = str(tmp_path / "snap.tar.zst")
    agave_snapshot_write(path, m, {(1000, 0): vec})
    funk, _m, _s = agave_snapshot_load(path)

    t = ft.transfer_txn(secret, _h("dest"), 777, _h("bh1"), from_pubkey=payer)
    res = execute_block(funk, slot=1001, txns=[t],
                        parent_bank_hash=_h("bank"), publish=True)
    assert res.results[0].status == TXN_SUCCESS
    assert acct_lamports(funk.rec_query(None, _h("dest"))) == 777
