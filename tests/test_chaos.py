"""The chaos harness (ISSUE 7): scenario determinism, invariant
checking, fault injection through the real supervisor, the tango lossy
shim, and the teardown hygiene the harness's reclaim invariant rides on.

Tier-1 runs the cheap scenarios at reduced scale; the full catalog at
production scale (1k-client storm, two-slot leader handoff) rides the
slow marker and the CI chaos-smoke job runs the two cheapest end to end
via the CLI."""

import json
import os

import pytest

from firedancer_tpu.chaos import faults as cf
from firedancer_tpu.chaos import invariants as inv
from firedancer_tpu.chaos import scenario as cs
from firedancer_tpu.tango import shm
from firedancer_tpu.utils.rng import Rng


# -- the lossy shim -----------------------------------------------------------


def _mk_link(tag, depth=256, mtu=128):
    return shm.ShmLink.create(
        f"fdtpu_tchaos_{tag}_{os.getpid()}", depth=depth, mtu=mtu)


def test_lossy_consumer_drop_dup_reorder_deterministic():
    from firedancer_tpu.tango.lossy import LossyConsumer

    def run(seed):
        link = _mk_link(f"lossy{seed}")
        try:
            prod = shm.Producer(link)
            cons = LossyConsumer(shm.Consumer(link, lazy=8), Rng(seed, 1),
                                 drop_p=0.2, dup_p=0.15, reorder_p=0.25)
            sent = [b"frag-%03d" % i for i in range(120)]
            got = []
            i = 0
            while True:
                if i < len(sent):
                    prod.try_publish(sent[i], sig=i)
                    i += 1
                r = cons.poll()
                if isinstance(r, tuple):
                    got.append(bytes(r[1]))
                elif i >= len(sent):
                    r2 = cons.poll()  # one more: flush shim-held frags
                    if isinstance(r2, tuple):
                        got.append(bytes(r2[1]))
                    else:
                        break
            return got, cons.dropped, cons.duplicated, cons.reordered
        finally:
            link.close()
            link.unlink()

    got1, d1, u1, r1 = run(5)
    got2, d2, u2, r2 = run(5)
    assert (got1, d1, u1, r1) == (got2, d2, u2, r2)  # seed-replayable
    assert d1 > 0 and u1 > 0 and r1 > 0  # every fault kind fired
    # conservation: delivered + dropped == sent + duplicated
    assert len(got1) + d1 == 120 + u1
    # no corruption, no invention
    assert set(got1) <= {b"frag-%03d" % i for i in range(120)}


# -- invariant machinery ------------------------------------------------------


def test_invariant_suite_and_violation_artifact(tmp_path, monkeypatch):
    suite = inv.InvariantSuite()
    assert suite.check("good", True)
    assert not suite.check("bad", False, "broke")
    assert not suite.ok
    assert [c.name for c in suite.violations()] == ["bad"]
    assert suite.summary() == {"bad": False, "good": True}
    with pytest.raises(inv.InvariantViolation):
        suite.require("worse", False, "very")
    # a violated cooperative scenario captures flight + trace artifacts
    monkeypatch.setenv("FDTPU_RUN_DIR", str(tmp_path))
    import importlib

    from firedancer_tpu.runtime import monitor as mon

    importlib.reload(mon)
    try:
        from firedancer_tpu.runtime.stage import Stage

        st = Stage("lonely")
        result = cs.ScenarioResult("unit", 3, suite)
        cs._capture_coop_failure(result, [st])
        assert len(result.artifacts) == 2
        flight, trace = result.artifacts
        dump = json.load(open(flight))
        assert "lonely" in dump["stages"]
        assert "worse" in dump["reason"] and "bad" in dump["reason"]
        tr = json.load(open(trace))
        assert tr["traceEvents"]
    finally:
        monkeypatch.delenv("FDTPU_RUN_DIR")
        importlib.reload(mon)


def test_payload_digest_order_independent():
    a = [b"x", b"yy", b"zzz"]
    assert inv.payload_digest(a) == inv.payload_digest(list(reversed(a)))
    assert inv.payload_digest(a) != inv.payload_digest(a[:2])


def test_conservation_check_catches_a_leak():
    suite = inv.InvariantSuite()
    report = {
        "benchg": {"txn_gen": 10},
        "verify0": {"txn_verified": 9},  # one txn vanished unexplained
        "dedup": {"dedup_dup": 0},
        "pack": {"txn_in": 9, "txn_scheduled": 9, "microblocks": 2,
                 "microblock_done": 2},
        "bank0": {"txn_exec": 9},
    }
    inv.check_pipeline_conservation(suite, report, 9)
    assert not suite.ok
    assert "verify-accounts-for-generated" in [
        c.name for c in suite.violations()]


# -- scenarios (tier-1 scale) -------------------------------------------------


def test_dedup_flood_scenario_deterministic():
    r1 = cs.run_dedup_flood(seed=11, duration=20)
    assert r1.ok, r1.suite.describe()
    r2 = cs.run_dedup_flood(seed=11, duration=20)
    assert r1.summary() == r2.summary()
    # the fault injection really fired
    assert r1.info["shim_duplicated"] > 0
    assert r1.info["shim_reordered"] > 0


def test_fork_storm_scenario_deterministic_and_seed_sensitive():
    r1 = cs.run_fork_storm(seed=11)
    assert r1.ok, r1.suite.describe()
    assert cs.run_fork_storm(seed=11).summary() == r1.summary()
    r3 = cs.run_fork_storm(seed=12)
    assert r3.ok
    assert r3.summary()["info"] != r1.summary()["info"]


def test_connection_storm_small_scale():
    """Tier-1 slice of the acceptance storm: the full >=1k population
    rides the slow matrix; the machinery (retry gate statelessness,
    budget audit, honest delivery through the gate) is identical."""
    from firedancer_tpu.runtime import net_native

    r = cs.run_connection_storm(seed=11, duration=60, n_clients=48,
                                n_honest=3)
    assert r.ok, r.suite.describe()
    assert r.info["retry_tx"] == r.info["storm"] + r.info["honest"]
    assert r.info["amplification_capped"] is True
    # the native net lane (ISSUE 18): armed whenever the .so builds, and
    # every established honest conn moved onto the fast path
    assert r.info["net_native"] == net_native.available()
    if r.info["net_native"]:
        assert r.info["net_conn_exported"] == r.info["honest"]


@pytest.mark.slow
@pytest.mark.timeout(1200)
def test_connection_storm_10k_native():
    """The ISSUE 18 acceptance storm: 10k concurrent clients against the
    ingress with the native sweep client armed — RetryGate stays
    stateless, the 3x anti-amplification ledger holds from the outside,
    honest txns land exactly once over the native lane, and the
    per-seed summary diffs clean across two full runs."""
    r1 = cs.run_connection_storm(seed=7, duration=600, n_clients=10000,
                                 n_honest=32)
    assert r1.ok, r1.suite.describe()
    checks = r1.summary()["checks"]
    for name in ("retry-per-untokened-initial",
                 "storm-allocates-no-connections",
                 "amplification-budget-held",
                 "honest-txns-delivered-exactly-once"):
        assert checks[name], name
    from firedancer_tpu.runtime import net_native

    assert r1.info["net_native"] == net_native.available()
    if r1.info["net_native"]:
        assert r1.info["net_conn_exported"] == r1.info["honest"]
    r2 = cs.run_connection_storm(seed=7, duration=600, n_clients=10000,
                                 n_honest=32)
    assert r1.summary() == r2.summary()


def test_stage_kill_scenario_and_restart():
    """ISSUE 7 satellite: kill one stage mid-run -> the topology fails
    fast naming the victim, the flight dump exists as the failure
    artifact, every /dev/shm segment is reclaimed after close(), and a
    restart runs clean."""
    r = cs.run_stage_kill(seed=11, duration=30)
    assert r.ok, r.suite.describe()
    checks = r.summary()["checks"]
    for name in ("supervisor-fails-fast", "victim-identified",
                 "flight-dump-written", "shm-reclaimed",
                 "restart-runs-clean", "restart-shm-reclaimed",
                 "shm-registry-conservation"):
        assert checks[name], name
    # the dump + trace landed as artifacts
    assert any(a.endswith("_trace.json") for a in r.artifacts)
    for a in r.artifacts:
        if "flight" in os.path.basename(a):
            os.remove(a)  # dumps outlive runs by design; tidy the host


def test_freeze_fault_detected_by_stale_heartbeat():
    """The wedge fault: SIGSTOP keeps the process alive but silences its
    cnc heartbeat — the supervisor must kill the topology on staleness,
    and close() must still reclaim every segment (the SIGCONT-before-
    terminate path)."""
    from firedancer_tpu.runtime import topo as ft

    h = ft.launch(cs._kill_topology(limit=1_000_000))
    names = h.shm_names()
    try:
        assert cs._wait_registry(h, "sink", "frags_in", 32, timeout_s=30)
        inj = cf.FaultInjector([cf.FreezeStage("relay", at_s=0.05)]).arm()
        ok = h.supervise(until=lambda hh: False, timeout_s=30,
                         heartbeat_timeout_s=1.0, on_poll=inj)
        assert ok is False
        assert h.failed == "relay"
        assert inj.all_fired()
        assert h.flight_dump_path and os.path.exists(h.flight_dump_path)
        os.remove(h.flight_dump_path)
    finally:
        h.close()
    suite = inv.InvariantSuite()
    inv.check_shm_reclaimed(suite, names)
    assert suite.ok, suite.describe()


# -- the CLI ------------------------------------------------------------------


def test_chaos_cli_run_is_deterministic(capsys):
    from firedancer_tpu.__main__ import main

    rc1 = main(["chaos", "run", "dedup-flood", "--seed", "7",
                "--duration", "20"])
    out1 = capsys.readouterr().out
    rc2 = main(["chaos", "run", "dedup-flood", "--seed", "7",
                "--duration", "20"])
    out2 = capsys.readouterr().out
    assert rc1 == rc2 == 0
    assert out1 == out2  # the replay contract, at the CLI surface
    doc = json.loads(out1)
    assert doc["scenario"] == "dedup-flood" and doc["ok"] is True
    # the summary artifact landed at the deterministic path
    assert os.path.exists(os.path.join(
        cs._run_dir(), "fdtpu_chaos_dedup-flood_s7.json"))


def test_chaos_cli_list_and_unknown(capsys):
    from firedancer_tpu.__main__ import main

    assert main(["chaos", "list"]) == 0
    out = capsys.readouterr().out
    for name in cs.SCENARIOS:
        assert name in out
    assert main(["chaos", "run", "no-such-scenario"]) == 2


# -- teardown hygiene (ISSUE 7 satellite: the BENCH-tail fix) -----------------


def test_pipeline_close_drops_every_shm_view():
    """LeaderPipeline.close() must leave every link's SharedMemory fully
    closed (fd gone, buffer released): a pinned view here is exactly the
    'BufferError: cannot close exported pointers exist' spray that
    polluted the BENCH_r03-05 artifact tails at interpreter exit."""
    from firedancer_tpu.models.leader import build_leader_pipeline

    pipe = build_leader_pipeline(n_verify=1, n_bank=1, pool_size=4,
                                 gen_limit=4, batch=8, max_msg_len=256)
    pipe.close()
    for link in pipe.links:
        assert link._shm._buf is None
        assert getattr(link._shm, "_fd", -1) == -1


def test_shmlink_close_survives_external_view(tmp_path):
    """An external attacher still holding a view must not be able to
    turn close() into exit noise: the wrapper detaches so its __del__
    is a no-op, and unlink still reclaims the name."""
    link = _mk_link("extview")
    external = shm.Consumer(link, lazy=8)  # pins fseq views
    name = link._shm.name
    link.close()
    link.unlink()
    assert not os.path.exists(os.path.join("/dev/shm", name))
    # the wrapper can no longer raise from __del__
    assert link._shm._mmap is None or link._shm._buf is None
    del external


# -- the full catalog (production scale) --------------------------------------


@pytest.mark.slow
@pytest.mark.timeout(1200)
@pytest.mark.parametrize("name", sorted(cs.SCENARIOS))
def test_scenario_matrix_full_scale(name):
    """Every named scenario at its production defaults — including the
    >=1k-client connection storm (the acceptance bar) and the two-slot
    leader handoff with its XLA compiles."""
    r = cs.run_scenario(name, seed=7)
    assert r.ok, f"{name}:\n{r.suite.describe()}"


# -- slot-clock plane scenarios (ISSUE 14) ------------------------------------


def test_crash_mid_slot_scenario():
    """In-place restart under the slot clock: two SIGKILLs mid-slot are
    absorbed by the restart policy (exactly-once stream diff across
    both), the slot clock never misses a beat, and the crash-loop flank
    degrades to the fail-fast + flight-dump path within the bounded
    attempts — the ISSUE 14 acceptance pair in one scenario run."""
    r = cs.run_crash_mid_slot(seed=11, n_frags=2000, n_slots=4,
                              slot_ms=250.0, boot_grace_s=4.0)
    assert r.ok, r.suite.describe()
    checks = r.summary()["checks"]
    for name in ("both-kills-fired", "kills-landed-mid-stream",
                 "relay-restarted-in-place", "exactly-once-no-loss",
                 "exactly-once-no-dup", "stream-order-preserved",
                 "crash-cost-no-slots", "crash-loop-fails-fast",
                 "crash-loop-attempts-bounded",
                 "crash-loop-flight-dump-written", "shm-reclaimed",
                 "crash-loop-shm-reclaimed"):
        assert checks[name], name
    assert r.info["restarts"] == 2
    # the backoff schedule in the summary is the POLICY's deterministic
    # one: reproducible from (seed, stage) alone
    from firedancer_tpu.runtime.restart import RestartPolicy

    pol = RestartPolicy(max_restarts=3, backoff_base_s=0.03, seed=11)
    assert r.info["restart_schedule_ms"] == [
        round(d * 1e3, 3) for d in pol.schedule("relay")]


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_slot_overrun_scenario_deterministic():
    """The full leader topology on the wall clock, twice with one seed:
    identical summaries (the chaos determinism contract), the frozen
    boundaries always exactly two missed slots."""
    a = cs.run_slot_overrun(seed=7)
    assert a.ok, a.suite.describe()
    b = cs.run_slot_overrun(seed=7)
    assert a.summary() == b.summary()
    assert a.info["missed"] == 2
