"""Executor + VM completion tests: bpf-to-bpf calls, memops/alloc
syscalls, the aligned account serialization, sBPF programs executing
against runtime accounts, and CPI (sol_invoke_signed_c) with privilege
enforcement — the fd_executor.c / fd_vm_syscall_cpi.c surface."""

import pytest

from firedancer_tpu.flamenco import vm as fvm
from firedancer_tpu.flamenco.executor import (
    Account,
    BPF_LOADER_PROGRAM,
    Executor,
    InstrAccount,
    InstrError,
    TxnCtx,
    serialize_aligned,
)
from firedancer_tpu.flamenco.programs import AcctError, FundsError
from firedancer_tpu.protocol import sbpf
from firedancer_tpu.protocol.txn import SYSTEM_PROGRAM
from tests.test_sbpf import build_elf, ins
from tests.test_vm import run_text

EXIT = ins(0x95)


def lddw(dst, val):
    return (
        ins(0x18, dst=dst, imm=val & 0xFFFFFFFF)
        + bytes(4)
        + ((val >> 32) & 0xFFFFFFFF).to_bytes(4, "little")
    )


# -- VM: function calls and frames -------------------------------------------


def test_bpf_to_bpf_call_and_frame_isolation():
    # main: r6=5; call f; r0 = r6 + r0  (f clobbers its own r6, returns 37)
    text = (
        ins(0xB7, dst=6, imm=5)            # mov r6, 5
        + ins(0x85, src=1, imm=2)          # call +2 (f at pc 4)
        + ins(0x0F, dst=0, src=6)          # add r0, r6  -> 37 + 5
        + EXIT
        # f:
        + ins(0xB7, dst=6, imm=1000)       # clobber r6 in the callee
        + ins(0xB7, dst=0, imm=37)
        + EXIT                             # pops the frame
    )
    assert run_text(text).run() == 42


def test_callx_via_register():
    prog = sbpf.load(build_elf(ins(0xB7, dst=0, imm=0) + EXIT))
    # f is at pc 5 (lddw below occupies two slots)
    text = (
        lddw(1, fvm.MM_PROGRAM + prog.text_off + 5 * 8)
        + ins(0x8D, imm=1)                 # callx r1
        + ins(0x07, dst=0, imm=1)          # r0 += 1 after return
        + EXIT
        + ins(0xB7, dst=0, imm=9)          # f: r0 = 9
        + EXIT
    )
    assert run_text(text).run() == 10


def test_call_depth_limit():
    # f calls itself forever -> depth error before budget at small budget
    text = ins(0x85, src=1, imm=-1) + EXIT
    with pytest.raises(fvm.VmError, match="depth"):
        run_text(text, budget=100_000).run()


def test_exit_from_outermost_returns():
    assert run_text(ins(0xB7, dst=0, imm=3) + EXIT).run() == 3


# -- VM: memops + alloc syscalls ----------------------------------------------


def _with_syscalls(text, **kw):
    m = run_text(text, **kw)
    fvm.register_default_syscalls(m)
    return m


def test_memset_memcpy_memcmp():
    text = (
        # memset([r10-16], 0xAB, 8)
        ins(0xBF, dst=1, src=10) + ins(0x07, dst=1, imm=-16)
        + ins(0xB7, dst=2, imm=0xAB) + ins(0xB7, dst=3, imm=8)
        + ins(0x85, imm=fvm.SYSCALL_SOL_MEMSET)
        # memcpy([r10-8], [r10-16], 8)
        + ins(0xBF, dst=1, src=10) + ins(0x07, dst=1, imm=-8)
        + ins(0xBF, dst=2, src=10) + ins(0x07, dst=2, imm=-16)
        + ins(0xB7, dst=3, imm=8)
        + ins(0x85, imm=fvm.SYSCALL_SOL_MEMCPY)
        # memcmp([r10-8], [r10-16], 8) -> result u32 at [r10-24]
        + ins(0xBF, dst=1, src=10) + ins(0x07, dst=1, imm=-8)
        + ins(0xBF, dst=2, src=10) + ins(0x07, dst=2, imm=-16)
        + ins(0xB7, dst=3, imm=8)
        + ins(0xBF, dst=4, src=10) + ins(0x07, dst=4, imm=-24)
        + ins(0x85, imm=fvm.SYSCALL_SOL_MEMCMP)
        + ins(0x61, dst=0, src=10, off=-24)  # r0 = cmp result (0 = equal)
        + EXIT
    )
    assert _with_syscalls(text).run() == 0


def test_memcpy_overlap_faults():
    text = (
        ins(0xBF, dst=1, src=10) + ins(0x07, dst=1, imm=-12)
        + ins(0xBF, dst=2, src=10) + ins(0x07, dst=2, imm=-16)
        + ins(0xB7, dst=3, imm=8)
        + ins(0x85, imm=fvm.SYSCALL_SOL_MEMCPY)
        + EXIT
    )
    with pytest.raises(fvm.VmError, match="overlap"):
        _with_syscalls(text).run()


def test_alloc_free_bump():
    # two 16-byte allocations: distinct, heap-region addresses
    text = (
        ins(0xB7, dst=1, imm=16) + ins(0xB7, dst=2, imm=0)
        + ins(0x85, imm=fvm.SYSCALL_SOL_ALLOC_FREE)
        + ins(0xBF, dst=6, src=0)
        + ins(0xB7, dst=1, imm=16) + ins(0xB7, dst=2, imm=0)
        + ins(0x85, imm=fvm.SYSCALL_SOL_ALLOC_FREE)
        + ins(0x1F, dst=0, src=6)          # r0 = second - first
        + EXIT
    )
    m = _with_syscalls(text)
    assert m.run() == 16


def test_log_64_and_data(capsys=None):
    logs = []
    text = (
        ins(0xB7, dst=1, imm=1) + ins(0xB7, dst=2, imm=2)
        + ins(0xB7, dst=3, imm=3) + ins(0xB7, dst=4, imm=4)
        + ins(0xB7, dst=5, imm=5)
        + ins(0x85, imm=fvm.SYSCALL_SOL_LOG_64)
        + ins(0x85, imm=fvm.SYSCALL_SOL_LOG_CU)
        + ins(0xB7, dst=0, imm=0)
        + EXIT
    )
    m = run_text(text)
    fvm.register_default_syscalls(m, log_sink=logs)
    assert m.run() == 0
    assert logs[0] == b"0x1, 0x2, 0x3, 0x4, 0x5"
    assert logs[1].startswith(b"consumed ")


# -- executor: native program dispatch ----------------------------------------


def _ctx(*accts, signer=None, writable=None):
    accounts = list(accts)
    n = len(accounts)
    return TxnCtx(
        accounts=accounts,
        signer=signer if signer is not None else [True] * n,
        writable=writable if writable is not None else [True] * n,
    )


def _sys_acct(key, lamports, data=b""):
    return Account(key, lamports, SYSTEM_PROGRAM, False, bytearray(data))


def _transfer_ix(lamports):
    return (2).to_bytes(4, "little") + lamports.to_bytes(8, "little")


def test_system_transfer_and_conservation():
    ex = Executor()
    ctx = _ctx(_sys_acct(b"A" * 32, 1000), _sys_acct(b"B" * 32, 0))
    ex.execute_instr(
        ctx, SYSTEM_PROGRAM,
        [InstrAccount(0, True, True), InstrAccount(1, False, True)],
        _transfer_ix(400),
    )
    assert ctx.accounts[0].lamports == 600
    assert ctx.accounts[1].lamports == 400


def test_system_transfer_requires_signer():
    ex = Executor()
    ctx = _ctx(_sys_acct(b"A" * 32, 1000), _sys_acct(b"B" * 32, 0))
    with pytest.raises(AcctError, match="signature"):
        ex.execute_instr(
            ctx, SYSTEM_PROGRAM,
            [InstrAccount(0, False, True), InstrAccount(1, False, True)],
            _transfer_ix(1),
        )


def test_system_create_assign_allocate():
    ex = Executor()
    owner = b"P" * 32
    ctx = _ctx(_sys_acct(b"A" * 32, 10_000), _sys_acct(b"N" * 32, 0))
    create = (
        (0).to_bytes(4, "little")
        + (5_000).to_bytes(8, "little")
        + (64).to_bytes(8, "little")
        + owner
    )
    ex.execute_instr(
        ctx, SYSTEM_PROGRAM,
        [InstrAccount(0, True, True), InstrAccount(1, True, True)],
        create,
    )
    new = ctx.accounts[1]
    assert (new.lamports, new.owner, len(new.data)) == (5_000, owner, 64)
    # creating over an existing account fails
    with pytest.raises(AcctError, match="in use"):
        ex.execute_instr(
            ctx, SYSTEM_PROGRAM,
            [InstrAccount(0, True, True), InstrAccount(1, True, True)],
            create,
        )
    # allocate + assign on a fresh system account
    ctx2 = _ctx(_sys_acct(b"Z" * 32, 0))
    ex.execute_instr(
        ctx2, SYSTEM_PROGRAM, [InstrAccount(0, True, True)],
        (8).to_bytes(4, "little") + (32).to_bytes(8, "little"),
    )
    assert len(ctx2.accounts[0].data) == 32
    ex.execute_instr(
        ctx2, SYSTEM_PROGRAM, [InstrAccount(0, True, True)],
        (1).to_bytes(4, "little") + owner,
    )
    assert ctx2.accounts[0].owner == owner


def test_insufficient_funds_typed():
    ex = Executor()
    ctx = _ctx(_sys_acct(b"A" * 32, 10), _sys_acct(b"B" * 32, 0))
    with pytest.raises(FundsError):
        ex.execute_instr(
            ctx, SYSTEM_PROGRAM,
            [InstrAccount(0, True, True), InstrAccount(1, False, True)],
            _transfer_ix(100),
        )


# -- executor: sBPF programs over serialized accounts -------------------------


def _bpf_program_account(key, text):
    return Account(key, 1, BPF_LOADER_PROGRAM, True, bytearray(build_elf(text)))


def _serial_offsets(n_data: int) -> dict:
    """Input-region offsets for instruction account 0 with data_len
    n_data (aligned layout)."""
    base = 8
    return {
        "key": base + 8,
        "owner": base + 40,
        "lamports": base + 72,
        "data_len": base + 80,
        "data": base + 88,
    }


def test_bpf_program_mutates_account_data():
    # program: input[data] = 0x2A on account 0; return 0
    off = _serial_offsets(8)
    text = (
        lddw(1, fvm.MM_INPUT + off["data"])
        + ins(0xB7, dst=2, imm=0x2A)
        + ins(0x73, dst=1, src=2)          # stxb [r1], r2
        + ins(0xB7, dst=0, imm=0)
        + EXIT
    )
    prog_key = b"p" * 32
    ex = Executor()
    # the mutated account is owned by the program (owner-may-modify rule)
    acct = Account(b"D" * 32, 5, prog_key, False, bytearray(8))
    ctx = _ctx(
        acct,
        _bpf_program_account(prog_key, text),
        writable=[True, False],
    )
    ex.execute_instr(ctx, prog_key, [InstrAccount(0, False, True)], b"")
    assert ctx.accounts[0].data[0] == 0x2A


def test_bpf_program_nonzero_return_is_error():
    text = ins(0xB7, dst=0, imm=7) + EXIT
    prog_key = b"p" * 32
    ex = Executor()
    ctx = _ctx(
        _sys_acct(b"D" * 32, 5),
        _bpf_program_account(prog_key, text),
        writable=[True, False],
    )
    with pytest.raises(InstrError, match="program error"):
        ex.execute_instr(ctx, prog_key, [InstrAccount(0, False, True)], b"")


def test_bpf_readonly_account_write_fails_instruction():
    # program writes its view of a READONLY account: the instruction
    # FAILS (ReadonlyDataModified parity — silently dropping the write
    # would let a program "succeed" while its effects vanish; r4 vm
    # conformance fixture store_readonly_faults pinned this)
    off = _serial_offsets(8)
    text = (
        lddw(1, fvm.MM_INPUT + off["lamports"])
        + ins(0xB7, dst=2, imm=999)
        + ins(0x7B, dst=1, src=2)          # stxdw [r1], r2
        + ins(0xB7, dst=0, imm=0)
        + EXIT
    )
    prog_key = b"p" * 32
    ex = Executor()
    ctx = _ctx(
        _sys_acct(b"D" * 32, 5, bytes(8)),
        _bpf_program_account(prog_key, text),
        writable=[False, False],
    )
    with pytest.raises(InstrError, match="read-only"):
        ex.execute_instr(ctx, prog_key, [InstrAccount(0, False, False)],
                         b"")
    assert ctx.accounts[0].lamports == 5  # unchanged


def test_serialize_dup_accounts():
    ctx = _ctx(_sys_acct(b"D" * 32, 5, b"xy"))
    blob, smap = serialize_aligned(
        ctx,
        [InstrAccount(0, True, True), InstrAccount(0, True, True)],
        b"ix",
        b"q" * 32,
    )
    assert blob[:8] == (2).to_bytes(8, "little")
    assert len(smap) == 1  # dup serialized as a 1-byte back-reference
    assert blob[8 + 8 + 32 + 32 + 8 + 8 : 8 + 8 + 32 + 32 + 8 + 8 + 2] == b"xy"


# -- CPI ----------------------------------------------------------------------


def _cpi_caller_text(callee_prog_id_addr, acct_key_addr, *, signer=0):
    """Builds SolAccountMeta + SolInstruction on the stack and invokes."""
    return (
        # meta at [r10-64]: pubkey_addr | is_writable=1 | is_signer
        lddw(1, acct_key_addr)
        + ins(0x7B, dst=10, src=1, off=-64)
        + ins(0xB7, dst=1, imm=1)
        + ins(0x73, dst=10, src=1, off=-56)
        + ins(0xB7, dst=1, imm=signer)
        + ins(0x73, dst=10, src=1, off=-55)
        # instruction at [r10-48]
        + lddw(1, callee_prog_id_addr)
        + ins(0x7B, dst=10, src=1, off=-48)   # program_id_addr
        + ins(0xBF, dst=1, src=10) + ins(0x07, dst=1, imm=-64)
        + ins(0x7B, dst=10, src=1, off=-40)   # accounts_addr
        + ins(0xB7, dst=1, imm=1)
        + ins(0x7B, dst=10, src=1, off=-32)   # accounts_len = 1
        + ins(0xB7, dst=1, imm=0)
        + ins(0x7B, dst=10, src=1, off=-24)   # data_addr = 0
        + ins(0x7B, dst=10, src=1, off=-16)   # data_len = 0
        # invoke(&instr, NULL, 0, NULL, 0)
        + ins(0xBF, dst=1, src=10) + ins(0x07, dst=1, imm=-48)
        + ins(0xB7, dst=2, imm=0) + ins(0xB7, dst=3, imm=0)
        + ins(0xB7, dst=4, imm=0) + ins(0xB7, dst=5, imm=0)
        + ins(0x85, imm=fvm.SYSCALL_SOL_INVOKE_SIGNED_C)
        + ins(0xB7, dst=0, imm=0)
        + EXIT
    )


def _cpi_fixture(*, signer=0):
    """Caller BPF program CPIs into a native bump program that increments
    account 0's data[0].  Callee program id rides in the caller's
    instruction data; the target account key is read from the caller's
    own serialized input."""
    bump_id = b"B" * 32
    off = _serial_offsets(8)
    acct_entry_sz = 8 + 32 + 32 + 8 + 8 + 8 + 10 * 1024 + 8  # data_len 8
    instr_data_off = 8 + acct_entry_sz
    caller_text = _cpi_caller_text(
        fvm.MM_INPUT + instr_data_off + 8,  # prog id embedded in instr data
        fvm.MM_INPUT + off["key"],
        signer=signer,
    )
    prog_key = b"c" * 32
    ex = Executor()

    def bump(ex_, ctx_, pid, iaccts, data, *, pda_signers):
        a = ctx_.accounts[iaccts[0].txn_idx]
        if not iaccts[0].is_writable:
            raise InstrError("bump needs writable")
        a.data[0] += 1

    ex.register(bump_id, bump)
    ctx = _ctx(
        _sys_acct(b"D" * 32, 5, bytes(8)),
        _bpf_program_account(prog_key, caller_text),
        signer=[False, False],
        writable=[True, False],
    )
    return ex, ctx, prog_key, bump_id


def test_cpi_invokes_native_callee_and_syncs():
    ex, ctx, prog_key, bump_id = _cpi_fixture()
    ex.execute_instr(
        ctx, prog_key, [InstrAccount(0, False, True)], bump_id,
    )
    assert ctx.accounts[0].data[0] == 1


def test_cpi_signer_escalation_rejected():
    ex, ctx, prog_key, bump_id = _cpi_fixture(signer=1)
    with pytest.raises(InstrError, match="escalation"):
        ex.execute_instr(
            ctx, prog_key, [InstrAccount(0, False, True)], bump_id,
        )


def test_cpi_writable_escalation_rejected():
    ex, ctx, prog_key, bump_id = _cpi_fixture()
    # caller holds the account READONLY -> callee asking writable must die
    with pytest.raises(InstrError, match="escalation"):
        ex.execute_instr(
            ctx, prog_key, [InstrAccount(0, False, False)], bump_id,
        )


def test_cpi_rust_abi_invokes_callee():
    """sol_invoke_signed_rust: StableInstruction + 34-byte AccountMetas
    drive the same CPI core as the C path."""
    ex, ctx, prog_key, bump_id = _cpi_fixture_rust()
    ex.execute_instr(ctx, prog_key, [InstrAccount(0, False, True)], bump_id)
    assert ctx.accounts[0].data[0] == 1


def _cpi_fixture_rust():
    bump_id = b"B" * 32
    off = _serial_offsets(8)
    acct_entry_sz = 8 + 32 + 32 + 8 + 8 + 8 + 10 * 1024 + 8
    instr_data_off = 8 + acct_entry_sz
    prog_id_addr = fvm.MM_INPUT + instr_data_off + 8
    key_addr = fvm.MM_INPUT + off["key"]
    # build AccountMeta (34B) at [r10-104]: pubkey | is_signer=0 | is_writable=1
    # then StableInstruction (80B) at [r10-96..-16]:
    #   accounts {addr, cap, len} | data {addr, cap, len} | program_id 32B
    # program_id must be the VALUE (32 bytes), so copy it from instr data
    # via 4 u64 loads/stores
    text = (
        # meta pubkey: copy 32B from the serialized account key
        b"".join(
            lddw(2, key_addr + 8 * k)
            + ins(0x79, dst=3, src=2, off=0)
            + ins(0x7B, dst=10, src=3, off=-136 + 8 * k)
            for k in range(4)
        )
        + ins(0xB7, dst=3, imm=0)
        + ins(0x73, dst=10, src=3, off=-104)    # is_signer = 0
        + ins(0xB7, dst=3, imm=1)
        + ins(0x73, dst=10, src=3, off=-103)    # is_writable = 1
        # StableInstruction at [r10-96..-16] (fully below the frame top)
        + ins(0xBF, dst=3, src=10) + ins(0x07, dst=3, imm=-136)
        + ins(0x7B, dst=10, src=3, off=-96)     # accounts.addr
        + ins(0xB7, dst=3, imm=1)
        + ins(0x7B, dst=10, src=3, off=-88)     # accounts.cap = 1
        + ins(0x7B, dst=10, src=3, off=-80)     # accounts.len = 1
        + ins(0xB7, dst=3, imm=0)
        + ins(0x7B, dst=10, src=3, off=-72)     # data.addr = 0
        + ins(0x7B, dst=10, src=3, off=-64)     # data.cap = 0
        + ins(0x7B, dst=10, src=3, off=-56)     # data.len = 0
        # program_id value: copy 32B from instr data
        + b"".join(
            lddw(2, prog_id_addr + 8 * k)
            + ins(0x79, dst=3, src=2, off=0)
            + ins(0x7B, dst=10, src=3, off=-48 + 8 * k)
            for k in range(4)
        )
        + ins(0xBF, dst=1, src=10) + ins(0x07, dst=1, imm=-96)
        + ins(0xB7, dst=2, imm=0) + ins(0xB7, dst=3, imm=0)
        + ins(0xB7, dst=4, imm=0) + ins(0xB7, dst=5, imm=0)
        + ins(0x85, imm=fvm.SYSCALL_SOL_INVOKE_SIGNED_RUST)
        + ins(0xB7, dst=0, imm=0)
        + EXIT
    )
    prog_key = b"c" * 32
    ex = Executor()

    def bump(ex_, ctx_, pid, iaccts, data, *, pda_signers):
        a = ctx_.accounts[iaccts[0].txn_idx]
        if not iaccts[0].is_writable:
            raise InstrError("bump needs writable")
        a.data[0] += 1

    ex.register(bump_id, bump)
    ctx = _ctx(
        _sys_acct(b"D" * 32, 5, bytes(8)),
        _bpf_program_account(prog_key, text),
        signer=[False, False],
        writable=[True, False],
    )
    return ex, ctx, prog_key, bump_id
