"""Compute-budget ENFORCEMENT (the r3 gap: limits were parsed for pack
costing but the VM always ran with 200k).

Covers: SetComputeUnitLimit drives TxnCtx/VM budget through the full
runtime; a CU-limited txn aborts at its requested budget; RequestHeapFrame
sizes the VM heap; builtins charge their fixed cost."""

import hashlib

import pytest

from firedancer_tpu.flamenco.executor import (
    Account,
    BPF_LOADER_PROGRAM,
    Executor,
    InstrAccount,
    InstrError,
    TxnCtx,
)
from firedancer_tpu.flamenco.runtime import (
    TXN_ERR_PROGRAM,
    TXN_SUCCESS,
    acct_build,
    execute_block,
)
from firedancer_tpu.funk import Funk
from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.pack.cost import (
    COMPUTE_BUDGET_PROGRAM,
    DEFAULT_HEAP_SIZE,
    txn_budget,
)
from firedancer_tpu.protocol import txn as ft
from tests.test_sbpf import build_elf, ins


def keypair(tag: bytes):
    secret = hashlib.sha256(tag).digest()
    return secret, ref.public_key(secret)


def _bh(tag: bytes) -> bytes:
    return hashlib.sha256(tag).digest()


def _set_cu_limit(units: int) -> bytes:
    return bytes([2]) + units.to_bytes(4, "little")


def _req_heap(size: int) -> bytes:
    return bytes([1]) + size.to_bytes(4, "little")


def test_txn_budget_resolution():
    secret, payer = keypair(b"cb")
    prog_key = hashlib.sha256(b"cb-prog").digest()

    def build(cb_datas, n_other=1):
        instrs = [ft.InstrSpec(program_id=1, accounts=bytes([0]), data=d)
                  for d in cb_datas]
        instrs += [ft.InstrSpec(program_id=2, accounts=bytes([0]), data=b"x")
                   for _ in range(n_other)]
        msg = ft.message_build(
            version=ft.VLEGACY, signature_cnt=1, readonly_signed_cnt=0,
            readonly_unsigned_cnt=2,
            acct_addrs=[payer, COMPUTE_BUDGET_PROGRAM, prog_key],
            recent_blockhash=_bh(b"bh"), instrs=instrs,
        )
        p = ft.txn_assemble([ref.sign(secret, msg)], msg)
        return p, ft.txn_parse(p)

    # explicit limit wins
    p, t = build([_set_cu_limit(77_000)])
    assert txn_budget(p, t) == (77_000, DEFAULT_HEAP_SIZE)
    # default: 200k per instruction (including the CB instr itself, capped)
    p, t = build([], n_other=2)
    assert txn_budget(p, t) == (400_000, DEFAULT_HEAP_SIZE)
    # heap frame
    p, t = build([_req_heap(64 * 1024)])
    assert txn_budget(p, t) == (200_000, 64 * 1024)
    # duplicate SetComputeUnitLimit = malformed
    p, t = build([_set_cu_limit(1), _set_cu_limit(2)])
    assert txn_budget(p, t) is None


def _loop_elf(iters: int) -> bytes:
    """r1 = iters; loop { r1 -= 1; if r1 != 0 goto loop }; exit.
    Costs ~2*iters CU (one per insn)."""
    text = (
        ins(0xB7, dst=1, imm=iters)          # mov r1, iters
        + ins(0x17, dst=1, imm=1)            # sub r1, 1
        + ins(0x55, dst=1, off=-2, imm=0)    # jne r1, 0, -2
        + ins(0xB7, dst=0, imm=0)            # mov r0, 0
        + ins(0x95)                          # exit
    )
    return build_elf(text)


def test_cu_limited_txn_aborts_at_requested_budget():
    """e2e: same program, generous limit passes, tight limit aborts."""
    funk = Funk()
    secret, payer = keypair(b"cu-payer")
    funk.rec_insert(None, payer, acct_build(10_000_000))
    prog_key = hashlib.sha256(b"cu-prog").digest()
    funk.rec_insert(
        None, prog_key,
        acct_build(1, data=_loop_elf(5_000), owner=BPF_LOADER_PROGRAM,
                   executable=True),
    )

    def run(cu_limit, nonce):
        msg = ft.message_build(
            version=ft.VLEGACY, signature_cnt=1, readonly_signed_cnt=0,
            readonly_unsigned_cnt=2,
            acct_addrs=[payer, COMPUTE_BUDGET_PROGRAM, prog_key],
            recent_blockhash=_bh(b"bh%d" % nonce),
            instrs=[
                ft.InstrSpec(program_id=1, accounts=bytes([0]),
                             data=_set_cu_limit(cu_limit)),
                ft.InstrSpec(program_id=2, accounts=bytes([0]), data=b""),
            ],
        )
        txn = ft.txn_assemble([ref.sign(secret, msg)], msg)
        return execute_block(funk, slot=5 + nonce, txns=[txn]).results[0]

    ok = run(50_000, 0)  # ~10k CU needed
    assert ok.status == TXN_SUCCESS, ok
    tight = run(2_000, 1)  # loop needs ~10k: must abort, fee still paid
    assert tight.status == TXN_ERR_PROGRAM
    assert tight.fee > 0


def test_builtins_charge_fixed_cost():
    ex = Executor()
    a = Account(b"k" * 32, 1000, ft.SYSTEM_PROGRAM, False, bytearray())
    b = Account(b"j" * 32, 0, ft.SYSTEM_PROGRAM, False, bytearray())
    ctx = TxnCtx(accounts=[a, b], signer=[True, False],
                 writable=[True, True], budget=100)  # system costs 150
    ia = [InstrAccount(0, True, True), InstrAccount(1, False, True)]
    data = (2).to_bytes(4, "little") + (5).to_bytes(8, "little")
    with pytest.raises(InstrError, match="compute budget"):
        ex.execute_instr(ctx, ft.SYSTEM_PROGRAM, ia, data)
    ctx2 = TxnCtx(accounts=[a, b], signer=[True, False],
                  writable=[True, True], budget=1000)
    ex.execute_instr(ctx2, ft.SYSTEM_PROGRAM, ia, data)
    assert ctx2.cu_used == 150


def test_heap_frame_sizes_vm_heap():
    """sol_alloc_free_ can reach the requested heap, not one byte more."""
    from firedancer_tpu.flamenco import vm as fvm
    from firedancer_tpu.protocol import sbpf

    # call sol_alloc_free_(40*1024, 0) -> NULL under default heap,
    # non-NULL under a 64K RequestHeapFrame
    text = (
        ins(0xB7, dst=1, imm=40 * 1024)  # r1 = size
        + ins(0xB7, dst=2, imm=0)        # r2 = free_addr (0 = alloc)
        + ins(0x85, imm=fvm.SYSCALL_SOL_ALLOC_FREE)
        + ins(0x95)
    )
    prog = sbpf.load(build_elf(text))
    v = fvm.Vm(program=prog, budget=10_000)
    fvm.register_default_syscalls(v)
    assert v.run() == 0  # default 32K heap: allocation fails -> NULL
    v2 = fvm.Vm(program=prog, budget=10_000, heap_size=64 * 1024)
    fvm.register_default_syscalls(v2)
    assert v2.run() != 0
