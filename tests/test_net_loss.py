"""QUIC ingress e2e over a lossy link: handshake + txn delivery with 10%
of datagrams dropped in BOTH directions (the r3 verdict's 'done'
criterion for QUIC loss recovery)."""

import hashlib
import time

from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.tango import shm

import pytest

pytestmark = pytest.mark.slow  # XLA-compile/socket-heavy tier (see conftest)


def test_quic_ingress_delivers_over_10pct_loss():
    from firedancer_tpu.runtime.net import QuicIngressStage, QuicTxnClient

    uid = hashlib.sha256(b"loss-e2e").hexdigest()[:8]
    link = shm.ShmLink.create(f"fdtpu_loss_{uid}", depth=256, mtu=2400)
    identity = hashlib.sha256(b"loss-srv").digest()

    class Dropper:
        """Deterministic 10%: every 10th datagram vanishes."""

        def __init__(self):
            self.n = 0
            self.dropped = 0

        def __call__(self, dg: bytes) -> bool:
            self.n += 1
            if self.n % 10 == 0:
                self.dropped += 1
                return False
            return True

    srv_drop, cli_drop = Dropper(), Dropper()
    ingress = QuicIngressStage(
        "quic", outs=[shm.Producer(link)], rx_burst=32,
        identity_secret=identity, tx_filter=srv_drop,
    )
    sink = shm.Consumer(link, lazy=8)
    txns = [b"losstxn-%03d-" % i + bytes(range(64)) for i in range(20)]
    client = None
    try:
        import threading

        box = {}

        def connect():
            box["c"] = QuicTxnClient(
                ingress.addr, expected_peer=ref.public_key(identity),
                tx_filter=cli_drop, timeout_s=60,
            )

        t = threading.Thread(target=connect)
        t.start()
        deadline = time.monotonic() + 240
        while t.is_alive() and time.monotonic() < deadline:
            ingress.run_once()
            time.sleep(0.001)
        t.join(timeout=1)
        client = box["c"]

        for txn in txns:
            client.send_txn(txn)
        got = []
        deadline = time.monotonic() + 240
        while len(got) < len(txns) and time.monotonic() < deadline:
            ingress.run_once()
            client.pump()
            r = sink.poll()
            if isinstance(r, tuple):
                got.append(bytes(r[1]))
        assert len(got) == len(txns)
        assert set(got) == set(txns)
        # the lossy link actually dropped traffic in both directions
        assert srv_drop.dropped + cli_drop.dropped > 0
        # and retransmission eventually drains the client's sent state
        deadline = time.monotonic() + 60
        while client.unacked() and time.monotonic() < deadline:
            ingress.run_once()
            client.pump()
        assert not client.unacked()
    finally:
        if client is not None:
            client.close()
        ingress.close()


def test_server_side_pto_recovers_eaten_first_flight():
    """ISSUE 7 satellite: the server's ENTIRE first crypto flight is
    eaten by the link.  Only the server's own PTO (driven from
    after_credit's timer poll) can resend it — the client's Initial
    retransmission elicits nothing new from a server whose TLS pending
    buffers already drained.  The handshake completing at all is the
    proof the server-path recovery timers work; we additionally assert
    the server connection measured the path (RTT-adaptive PTO live)."""
    from firedancer_tpu.runtime.net import QuicIngressStage, QuicTxnClient

    uid = hashlib.sha256(b"srv-pto").hexdigest()[:8]
    link = shm.ShmLink.create(f"fdtpu_spto_{uid}", depth=128, mtu=2400)
    identity = hashlib.sha256(b"srv-pto-id").digest()

    class FirstFlightEater:
        """Swallows the server's first `n` datagrams (its whole initial
        crypto flight), then passes everything."""

        def __init__(self, n=3):
            self.left = n
            self.eaten = 0

        def __call__(self, dg: bytes) -> bool:
            if self.left > 0:
                self.left -= 1
                self.eaten += 1
                return False
            return True

    eater = FirstFlightEater()
    ingress = QuicIngressStage(
        "quic", outs=[shm.Producer(link)], rx_burst=32,
        identity_secret=identity, tx_filter=eater,
    )
    sink = shm.Consumer(link, lazy=8)
    client = None
    try:
        import threading

        box = {}

        def connect():
            box["c"] = QuicTxnClient(
                ingress.addr, expected_peer=ref.public_key(identity),
                timeout_s=120,
            )

        t = threading.Thread(target=connect)
        t.start()
        deadline = time.monotonic() + 240
        while t.is_alive() and time.monotonic() < deadline:
            ingress.run_once()
            time.sleep(0.001)
        t.join(timeout=1)
        assert "c" in box, "handshake never recovered from the eaten flight"
        client = box["c"]
        assert eater.eaten >= 1  # the flight really was eaten
        # exactly one server connection, and it measured the path: the
        # retransmission schedule is RTT-adaptive, not the fixed profile
        (conn,) = ingress.conns.values()
        assert conn.established
        assert conn.srtt is not None
        # same-host rtt << the 0.2s fixed profile (backoff-free base)
        from firedancer_tpu.waltz import quic

        assert conn.srtt + max(4 * conn.rttvar, quic.PTO_GRANULARITY_S) < 0.2
        # and a txn flows end to end over the recovered connection
        txn = b"srv-pto-txn-" + bytes(range(48))
        client.send_txn(txn)
        got = None
        deadline = time.monotonic() + 60
        while got is None and time.monotonic() < deadline:
            ingress.run_once()
            client.pump()
            r = sink.poll()
            if isinstance(r, tuple):
                got = bytes(r[1])
        assert got == txn
    finally:
        if client is not None:
            client.close()
        ingress.close()
        link.close()
        link.unlink()
