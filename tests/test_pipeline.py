"""End-to-end leader pipeline tests: gen -> verify(TPU) -> dedup -> pack ->
bank -> poh -> shred -> store on the CPU backend.  Asserts the full block
path: conflict-aware scheduling, REAL runtime execution over funk (fees,
status cache), bank-hash reproducibility from the wire entries alone,
PoH chain honesty (host + TPU segment verify), FEC sets reassembling
byte-identically."""

import hashlib

import numpy as np
import pytest

from firedancer_tpu.flamenco import runtime as rt
from firedancer_tpu.models.leader import build_leader_pipeline
from firedancer_tpu.protocol import txn as ft
from firedancer_tpu.runtime import poh as fpoh
from firedancer_tpu.runtime.benchg import pool_payers
from firedancer_tpu.runtime.poh_stage import parse_entry
from firedancer_tpu.runtime.shred_stage import deshred_entry_batch
from firedancer_tpu.runtime.verify import decode_verified, encode_verified


@pytest.fixture(scope="module")
def pipeline_result():
    """Run the full pipeline once; assert from multiple tests."""
    pipe = build_leader_pipeline(
        n_verify=1, n_bank=2, pool_size=96, gen_limit=96, batch=64,
        max_msg_len=256, slot=1,
    )
    try:
        pipe.run(until_txns=96, max_iters=200_000)
        report = pipe.report()
        seal = pipe.seal()
        ctx = pipe.bank_ctx
        balances = {
            a: rt.acct_lamports(ctx.funk.rec_query(ctx.sx.xid, a))
            for a in ctx.funk.rec_keys(ctx.sx.xid)
        }
        result = {
            "report": report,
            "entry_batch": pipe.store.entry_batch_bytes(1),
            "seal": seal,
            "balances": balances,
            "payers": [pub for _, pub in pool_payers()],
            "pool": list(pipe.benchg.pool),
            "n_sets_emitted": len(pipe.shred.sets),
        }
    finally:
        pipe.close()
    return result


def test_all_txns_reach_banks(pipeline_result):
    report = pipeline_result["report"]
    assert report["benchg"]["txn_gen"] == 96
    assert report["verify0"]["txn_verified"] == 96
    assert report["pack"]["txn_in"] == 96
    assert report["pack"]["txn_scheduled"] == 96
    execs = sum(report[f"bank{b}"].get("txn_exec", 0) for b in range(2))
    assert execs == 96
    # every scheduled microblock came back as a lock release
    assert report["pack"]["microblocks"] == report["pack"]["microblock_done"]


def test_bank_state_transitions(pipeline_result):
    """The REAL runtime executed the transfers against funk: payers paid
    lamports + fees, destinations received, lamports conserve."""
    seal = pipeline_result["seal"]
    payers = set(pipeline_result["payers"])
    balances = pipeline_result["balances"]
    total_sent = sum(1 + i for i in range(96))  # lamports = 1+i per txn
    assert seal.fees == 96 * rt.LAMPORTS_PER_SIGNATURE
    payer_spent = sum(
        10**12 - bal for a, bal in balances.items() if a in payers
    )
    dest_recv = sum(bal for a, bal in balances.items() if a not in payers)
    assert payer_spent == total_sent + seal.fees
    assert dest_recv == total_sent


def test_replay_reproduces_bank_hash(pipeline_result):
    """The wire entries alone replay to the SAME bank hash the live
    pipeline sealed — the leader's streaming execution and the validation
    path (flamenco/runtime.replay_block) agree on the state transition."""
    from firedancer_tpu.runtime.bank import default_bank_ctx

    batch = pipeline_result["entry_batch"]
    entries = [parse_entry(e) for e in deshred_entry_batch(batch)]
    ctx2 = default_bank_ctx(with_status_cache=False)
    from firedancer_tpu.flamenco.runtime import replay_block

    res = replay_block(
        ctx2.funk, slot=1, entries=entries, poh_seed=b"\x00" * 32,
    )
    assert res is not None, "PoH replay failed"
    assert res.bank_hash == pipeline_result["seal"].bank_hash
    assert res.signature_cnt == pipeline_result["seal"].signature_cnt == 96
    assert all(r.status == 0 for r in res.results)


def test_entry_batches_reassemble_and_carry_all_txns(pipeline_result):
    batch = pipeline_result["entry_batch"]
    assert len(batch) > 0
    entries = [parse_entry(e) for e in deshred_entry_batch(batch)]
    wire_txns = [p for _, _, txns in entries for p in txns]
    assert sorted(wire_txns) == sorted(pipeline_result["pool"])
    # ticks interleave with txn entries
    assert any(not txns for _, _, txns in entries)
    assert pipeline_result["n_sets_emitted"] == pipeline_result["report"][
        "store"
    ].get("sets_stored", 0)


def test_poh_chain_verifies_host_and_tpu(pipeline_result):
    batch = pipeline_result["entry_batch"]
    entries = [parse_entry(e) for e in deshred_entry_batch(batch)]
    ok, segments = fpoh.replay_entries(b"\x00" * 32, entries)
    assert ok, "PoH chain replay failed"
    assert segments
    # TPU batch-verify all equal-length segments (the wide verification
    # axis); host-check the stragglers
    from collections import defaultdict

    by_n = defaultdict(list)
    for start, n, end in segments:
        by_n[n].append((start, end))
    n, group = max(by_n.items(), key=lambda kv: len(kv[1]))
    starts = [s for s, _ in group]
    ends = [e for _, e in group]
    mask = fpoh.verify_segments_tpu(starts, n, ends)
    assert bool(np.asarray(mask).all())
    # corrupted end hash must fail
    bad_ends = [ends[0][:-1] + bytes([ends[0][-1] ^ 1])] + ends[1:]
    mask2 = np.asarray(fpoh.verify_segments_tpu(starts, n, bad_ends))
    assert not mask2[0] and mask2[1:].all()


def test_microblocks_respect_write_conflicts(pipeline_result):
    """No microblock contains two txns writing the same account."""
    batch = pipeline_result["entry_batch"]
    entries = [parse_entry(e) for e in deshred_entry_batch(batch)]
    for _, _, txns in entries:
        writable: set[bytes] = set()
        for p in txns:
            t = ft.txn_parse(p)
            addrs = t.acct_addrs(p)
            for i, a in enumerate(addrs):
                if t.is_writable(i):
                    assert a not in writable, "write conflict inside microblock"
                    writable.add(a)


def test_duplicates_are_dropped():
    # pool of 32 unique txns streamed 3x over -> dedup keeps 32
    pipe = build_leader_pipeline(
        n_verify=1, pool_size=32, gen_limit=96, batch=64, max_msg_len=256
    )
    try:
        # until_txns would stop generation at pack==32 — a FASTER dedup
        # then strands ungenerated dups (finish() zeroes benchg.limit).
        # Sweep on iterations instead so all 96 frags flow before drain.
        pipe.run(until_txns=None, max_iters=3_000)
        report = pipe.report()
        # the fused native lane counts dedup drops at pack (no dedup
        # stage in the topology); the python lane at the dedup stage
        dups = (
            report["verify0"].get("dedup_dup", 0)
            + report.get("dedup", {}).get("dedup_dup", 0)
            + report["pack"].get("dedup_dup", 0)
        )
        assert report["pack"]["txn_in"] == 32
        assert dups == 64
    finally:
        pipe.close()


@pytest.mark.slow  # second kernel shape (batch=32) = a second compile
def test_two_way_verify_fanout():
    pipe = build_leader_pipeline(
        n_verify=2, pool_size=64, gen_limit=64, batch=32, max_msg_len=256
    )
    try:
        pipe.run(until_txns=64, max_iters=200_000)
        report = pipe.report()
        v0 = report["verify0"]["txn_verified"]
        v1 = report["verify1"]["txn_verified"]
        assert v0 + v1 == 64
        assert v0 == 32 and v1 == 32  # strict round-robin by seq
        assert report["pack"]["txn_in"] == 64
    finally:
        pipe.close()


@pytest.mark.slow  # batch=32 kernel shape = a second ~4-min XLA compile
def test_corrupted_txn_dropped_by_kernel():
    from firedancer_tpu.runtime.benchg import gen_transfer_pool
    from firedancer_tpu.models import leader as ml

    pool = gen_transfer_pool(16)
    # corrupt one signature byte of txn 5: parses fine, fails sigverify
    bad = bytearray(pool[5])
    bad[10] ^= 0xFF
    pool[5] = bytes(bad)
    # and truncate txn 9: fails parse
    pool[9] = pool[9][:-3]

    pipe = ml.build_leader_pipeline(
        n_verify=1, pool_size=16, gen_limit=16, batch=32, max_msg_len=256
    )
    pipe.benchg.pool = pool
    try:
        pipe.run(until_txns=14, max_iters=200_000)
        report = pipe.report()
        assert report["verify0"]["parse_fail"] == 1
        assert report["verify0"]["verify_fail"] == 1
        assert report["verify0"]["txn_verified"] == 14
        assert report["pack"]["txn_in"] == 14
    finally:
        pipe.close()


def test_encode_decode_verified_roundtrip():
    from firedancer_tpu.runtime.benchg import gen_transfer_pool

    p = gen_transfer_pool(1)[0]
    t = ft.txn_parse(p)
    frag = encode_verified(p, t)
    p2, t2 = decode_verified(frag)
    assert p2 == p and t2 == t


@pytest.mark.slow  # third sigverify compile shape (~3.5 min on 1 core)
@pytest.mark.timeout(1200)
def test_mixed_workload_pipeline_replays_to_same_bank_hash():
    """The VERDICT r4 #1 done-criterion: a block containing system +
    vote + stake + BPF transactions flows benchg->verify->dedup->pack->
    bank->poh->shred->store, AND flamenco/runtime.replay_block
    independently reproduces the sealed bank hash from the wire
    entries."""
    import firedancer_tpu.flamenco.vm as fvm
    from firedancer_tpu.flamenco import agave_state as ast
    from firedancer_tpu.flamenco import stake as fstake
    from firedancer_tpu.flamenco import vote_program as vp
    from firedancer_tpu.flamenco.blockstore import StatusCache
    from firedancer_tpu.flamenco.executor import BPF_LOADER_PROGRAM
    from firedancer_tpu.flamenco.runtime import replay_block
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.runtime.bank import BankCtx
    from tests.test_sbpf import build_elf, ins

    bh = hashlib.sha256(b"mix-bh").digest()
    bank_hash_50 = hashlib.sha256(b"mix-bank-50").digest()
    slot_hashes = [(50, bank_hash_50)]

    def keypair(tag):
        secret = hashlib.sha256(tag).digest()
        return secret, ref.public_key(secret)

    pay_sec, payer = keypair(b"mix-payer")
    vot_sec, voter = keypair(b"mix-voter")
    stk_sec, staker = keypair(b"mix-staker")
    vote_acct = hashlib.sha256(b"mix-va").digest()
    stake_acct = hashlib.sha256(b"mix-sa").digest()
    bpf_prog = hashlib.sha256(b"mix-prog").digest()

    def genesis(ctx: BankCtx):
        from firedancer_tpu.flamenco.runtime import acct_build

        for pub in (payer, voter, staker):
            ctx.fund(pub, 10**12)
        init_vs = ast.VoteState(node_pubkey=voter,
                                authorized_withdrawer=voter,
                                authorized_voters={0: voter})
        ctx.funk.rec_insert(None, vote_acct, acct_build(
            10**9,
            data=ast.vote_state_encode(init_vs).ljust(vp.VOTE_STATE_SIZE,
                                                      b"\x00"),
            owner=ft.VOTE_PROGRAM))
        ctx.funk.rec_insert(None, stake_acct, acct_build(
            10**10, data=bytes(fstake._DATA_LEN),
            owner=fstake.STAKE_PROGRAM))
        # loader-v2 program: exit 0 (a real sBPF ELF through the VM)
        ctx.funk.rec_insert(None, bpf_prog, acct_build(
            1, data=build_elf(ins(0xB7, dst=0, imm=0) + ins(0x95)),
            owner=BPF_LOADER_PROGRAM, executable=True))

    def build_txns():
        out = [ft.transfer_txn(pay_sec, b"mx" * 16, 777, bh,
                               from_pubkey=payer)]
        out.append(ft.vote_txn(vot_sec, vote_acct, 50, bh,
                               bank_hash=bank_hash_50))
        # stake initialize (staker as both authorities)
        stake_data = (0).to_bytes(4, "little") + staker + staker
        msg = ft.message_build(
            version=ft.VLEGACY, signature_cnt=1, readonly_signed_cnt=0,
            readonly_unsigned_cnt=1,
            acct_addrs=[staker, stake_acct, fstake.STAKE_PROGRAM],
            recent_blockhash=bh,
            instrs=[ft.InstrSpec(program_id=2, accounts=bytes([1]),
                                 data=stake_data)])
        out.append(ft.txn_assemble([ref.sign(stk_sec, msg)], msg))
        # BPF invoke
        msg = ft.message_build(
            version=ft.VLEGACY, signature_cnt=1, readonly_signed_cnt=0,
            readonly_unsigned_cnt=1,
            acct_addrs=[payer, bpf_prog],
            recent_blockhash=bh,
            instrs=[ft.InstrSpec(program_id=1, accounts=b"",
                                 data=b"\x01")])
        out.append(ft.txn_assemble([ref.sign(pay_sec, msg)], msg))
        return out

    sc = StatusCache()
    sc.register_blockhash(bh, 50)
    ctx = BankCtx(slot=51, status_cache=sc)
    genesis(ctx)
    ctx.sx.sysvars["slot_hashes"] = __import__(
        "firedancer_tpu.flamenco.types", fromlist=["T"]
    ).SLOT_HASHES.encode([__import__(
        "firedancer_tpu.flamenco.types", fromlist=["T"]
    ).SlotHash(s, h) for s, h in slot_hashes])

    txns = build_txns()
    pipe = build_leader_pipeline(
        n_verify=1, n_bank=2, pool_size=4, gen_limit=len(txns), batch=8,
        max_msg_len=512, slot=51, bank_ctx=ctx, keep_entries=True,
    )
    pipe.benchg.pool = txns
    try:
        pipe.run(until_txns=len(txns), max_iters=200_000)
        report = pipe.report()
        execs = sum(report[f"bank{b}"].get("txn_exec", 0) for b in range(2))
        fails = sum(report[f"bank{b}"].get("txn_exec_failed", 0)
                    for b in range(2))
        assert execs == len(txns) and fails == 0, report
        seal = pipe.seal()
        # the vote LANDED on the tower
        from firedancer_tpu.flamenco.executor import acct_decode

        data = acct_decode(ctx.funk.rec_query(ctx.sx.xid, vote_acct))[3]
        vs = ast.vote_state_decode(data)
        assert [v.lockout.slot for v in vs.votes] == [50]

        # replay the WIRE entries on a fresh genesis: same bank hash
        entries = [parse_entry(e) for e in deshred_entry_batch(
            pipe.store.entry_batch_bytes(51))]
        ctx2 = BankCtx(slot=51)
        genesis(ctx2)
        res = replay_block(ctx2.funk, slot=51, entries=entries,
                           poh_seed=b"\x00" * 32,
                           slot_hashes=slot_hashes)
        assert res is not None
        assert res.bank_hash == seal.bank_hash
        assert all(r.status == 0 for r in res.results)
    finally:
        pipe.close()
