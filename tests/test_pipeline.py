"""End-to-end leader pipeline tests: gen -> verify(TPU) -> dedup -> pack ->
bank -> poh -> shred -> store on the CPU backend.  Asserts the full block
path: conflict-aware scheduling, REAL runtime execution over funk (fees,
status cache), bank-hash reproducibility from the wire entries alone,
PoH chain honesty (host + TPU segment verify), FEC sets reassembling
byte-identically."""

import hashlib

import numpy as np
import pytest

from firedancer_tpu.flamenco import runtime as rt
from firedancer_tpu.models.leader import build_leader_pipeline
from firedancer_tpu.protocol import txn as ft
from firedancer_tpu.runtime import poh as fpoh
from firedancer_tpu.runtime.benchg import pool_payers
from firedancer_tpu.runtime.poh_stage import parse_entry
from firedancer_tpu.runtime.shred_stage import deshred_entry_batch
from firedancer_tpu.runtime.verify import decode_verified, encode_verified


@pytest.fixture(scope="module")
def pipeline_result():
    """Run the full pipeline once; assert from multiple tests."""
    pipe = build_leader_pipeline(
        n_verify=1, n_bank=2, pool_size=96, gen_limit=96, batch=64,
        max_msg_len=256, slot=1,
    )
    try:
        pipe.run(until_txns=96, max_iters=200_000)
        report = pipe.report()
        seal = pipe.seal()
        ctx = pipe.bank_ctx
        balances = {
            a: rt.acct_lamports(ctx.funk.rec_query(ctx.sx.xid, a))
            for a in ctx.funk.rec_keys(ctx.sx.xid)
        }
        result = {
            "report": report,
            "entry_batch": pipe.store.entry_batch_bytes(1),
            "seal": seal,
            "balances": balances,
            "payers": [pub for _, pub in pool_payers()],
            "pool": list(pipe.benchg.pool),
            "n_sets_emitted": len(pipe.shred.sets),
        }
    finally:
        pipe.close()
    return result


def test_all_txns_reach_banks(pipeline_result):
    report = pipeline_result["report"]
    assert report["benchg"]["txn_gen"] == 96
    assert report["verify0"]["txn_verified"] == 96
    assert report["pack"]["txn_in"] == 96
    assert report["pack"]["txn_scheduled"] == 96
    execs = sum(report[f"bank{b}"].get("txn_exec", 0) for b in range(2))
    assert execs == 96
    # every scheduled microblock came back as a lock release
    assert report["pack"]["microblocks"] == report["pack"]["microblock_done"]


def test_bank_state_transitions(pipeline_result):
    """The REAL runtime executed the transfers against funk: payers paid
    lamports + fees, destinations received, lamports conserve."""
    seal = pipeline_result["seal"]
    payers = set(pipeline_result["payers"])
    balances = pipeline_result["balances"]
    total_sent = sum(1 + i for i in range(96))  # lamports = 1+i per txn
    assert seal.fees == 96 * rt.LAMPORTS_PER_SIGNATURE
    payer_spent = sum(
        10**12 - bal for a, bal in balances.items() if a in payers
    )
    dest_recv = sum(bal for a, bal in balances.items() if a not in payers)
    assert payer_spent == total_sent + seal.fees
    assert dest_recv == total_sent


def test_replay_reproduces_bank_hash(pipeline_result):
    """The wire entries alone replay to the SAME bank hash the live
    pipeline sealed — the leader's streaming execution and the validation
    path (flamenco/runtime.replay_block) agree on the state transition."""
    from firedancer_tpu.runtime.bank import default_bank_ctx

    batch = pipeline_result["entry_batch"]
    entries = [parse_entry(e) for e in deshred_entry_batch(batch)]
    ctx2 = default_bank_ctx(with_status_cache=False)
    from firedancer_tpu.flamenco.runtime import replay_block

    res = replay_block(
        ctx2.funk, slot=1, entries=entries, poh_seed=b"\x00" * 32,
    )
    assert res is not None, "PoH replay failed"
    assert res.bank_hash == pipeline_result["seal"].bank_hash
    assert res.signature_cnt == pipeline_result["seal"].signature_cnt == 96
    assert all(r.status == 0 for r in res.results)


def test_entry_batches_reassemble_and_carry_all_txns(pipeline_result):
    batch = pipeline_result["entry_batch"]
    assert len(batch) > 0
    entries = [parse_entry(e) for e in deshred_entry_batch(batch)]
    wire_txns = [p for _, _, txns in entries for p in txns]
    assert sorted(wire_txns) == sorted(pipeline_result["pool"])
    # ticks interleave with txn entries
    assert any(not txns for _, _, txns in entries)
    assert pipeline_result["n_sets_emitted"] == pipeline_result["report"][
        "store"
    ].get("sets_stored", 0)


def test_poh_chain_verifies_host_and_tpu(pipeline_result):
    batch = pipeline_result["entry_batch"]
    entries = [parse_entry(e) for e in deshred_entry_batch(batch)]
    ok, segments = fpoh.replay_entries(b"\x00" * 32, entries)
    assert ok, "PoH chain replay failed"
    assert segments
    # TPU batch-verify all equal-length segments (the wide verification
    # axis); host-check the stragglers
    from collections import defaultdict

    by_n = defaultdict(list)
    for start, n, end in segments:
        by_n[n].append((start, end))
    n, group = max(by_n.items(), key=lambda kv: len(kv[1]))
    starts = [s for s, _ in group]
    ends = [e for _, e in group]
    mask = fpoh.verify_segments_tpu(starts, n, ends)
    assert bool(np.asarray(mask).all())
    # corrupted end hash must fail
    bad_ends = [ends[0][:-1] + bytes([ends[0][-1] ^ 1])] + ends[1:]
    mask2 = np.asarray(fpoh.verify_segments_tpu(starts, n, bad_ends))
    assert not mask2[0] and mask2[1:].all()


def test_microblocks_respect_write_conflicts(pipeline_result):
    """No microblock contains two txns writing the same account."""
    batch = pipeline_result["entry_batch"]
    entries = [parse_entry(e) for e in deshred_entry_batch(batch)]
    for _, _, txns in entries:
        writable: set[bytes] = set()
        for p in txns:
            t = ft.txn_parse(p)
            addrs = t.acct_addrs(p)
            for i, a in enumerate(addrs):
                if t.is_writable(i):
                    assert a not in writable, "write conflict inside microblock"
                    writable.add(a)


def test_duplicates_are_dropped():
    # pool of 32 unique txns streamed 3x over -> dedup keeps 32
    pipe = build_leader_pipeline(
        n_verify=1, pool_size=32, gen_limit=96, batch=64, max_msg_len=256
    )
    try:
        # until_txns would stop generation at pack==32 — a FASTER dedup
        # then strands ungenerated dups (finish() zeroes benchg.limit).
        # Sweep on iterations instead so all 96 frags flow before drain.
        pipe.run(until_txns=None, max_iters=3_000)
        report = pipe.report()
        dups = report["verify0"].get("dedup_dup", 0) + report["dedup"].get(
            "dedup_dup", 0
        )
        assert report["pack"]["txn_in"] == 32
        assert dups == 64
    finally:
        pipe.close()


def test_two_way_verify_fanout():
    pipe = build_leader_pipeline(
        n_verify=2, pool_size=64, gen_limit=64, batch=32, max_msg_len=256
    )
    try:
        pipe.run(until_txns=64, max_iters=200_000)
        report = pipe.report()
        v0 = report["verify0"]["txn_verified"]
        v1 = report["verify1"]["txn_verified"]
        assert v0 + v1 == 64
        assert v0 == 32 and v1 == 32  # strict round-robin by seq
        assert report["pack"]["txn_in"] == 64
    finally:
        pipe.close()


def test_corrupted_txn_dropped_by_kernel():
    from firedancer_tpu.runtime.benchg import gen_transfer_pool
    from firedancer_tpu.models import leader as ml

    pool = gen_transfer_pool(16)
    # corrupt one signature byte of txn 5: parses fine, fails sigverify
    bad = bytearray(pool[5])
    bad[10] ^= 0xFF
    pool[5] = bytes(bad)
    # and truncate txn 9: fails parse
    pool[9] = pool[9][:-3]

    pipe = ml.build_leader_pipeline(
        n_verify=1, pool_size=16, gen_limit=16, batch=32, max_msg_len=256
    )
    pipe.benchg.pool = pool
    try:
        pipe.run(until_txns=14, max_iters=200_000)
        report = pipe.report()
        assert report["verify0"]["parse_fail"] == 1
        assert report["verify0"]["verify_fail"] == 1
        assert report["verify0"]["txn_verified"] == 14
        assert report["pack"]["txn_in"] == 14
    finally:
        pipe.close()


def test_encode_decode_verified_roundtrip():
    from firedancer_tpu.runtime.benchg import gen_transfer_pool

    p = gen_transfer_pool(1)[0]
    t = ft.txn_parse(p)
    frag = encode_verified(p, t)
    p2, t2 = decode_verified(frag)
    assert p2 == p and t2 == t
