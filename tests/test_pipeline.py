"""End-to-end leader pipeline tests: gen -> verify(TPU) -> dedup -> pack on
the CPU backend, including corruption drops, duplicate filtering, and
round-robin verify fan-out."""

import numpy as np
import pytest

from firedancer_tpu.models.leader import build_leader_pipeline
from firedancer_tpu.runtime.verify import decode_verified, encode_verified
from firedancer_tpu.protocol import txn as ft


@pytest.fixture(scope="module")
def small_pipeline_result():
    """Run once, assert from multiple tests (compiles one 64-batch kernel)."""
    pipe = build_leader_pipeline(
        n_verify=1, pool_size=96, gen_limit=96, batch=64, max_msg_len=256
    )
    try:
        pipe.run(until_txns=96, max_iters=200_000)
        report = pipe.report()
        microblocks = list(pipe.pack.microblocks)
    finally:
        pipe.close()
    return report, microblocks


def test_all_honest_txns_flow_through(small_pipeline_result):
    report, microblocks = small_pipeline_result
    assert report["benchg"]["txn_gen"] == 96
    assert report["verify0"]["txn_verified"] == 96
    assert report["verify0"].get("parse_fail", 0) == 0
    assert report["verify0"].get("verify_fail", 0) == 0
    assert report["dedup"].get("dedup_dup", 0) == 0
    assert report["pack"]["txn_in"] == 96
    total = sum(len(mb) for mb in microblocks)
    assert total == 96


def test_verified_frags_carry_descriptor(small_pipeline_result):
    _, microblocks = small_pipeline_result
    frame = microblocks[0][0]
    payload, desc = decode_verified(frame)
    assert ft.txn_parse(payload) is not None
    assert desc.signature_cnt == 1
    t = ft.txn_parse(payload)
    assert t.signature_off == desc.signature_off
    assert t.instrs == desc.instrs


def test_duplicates_are_dropped():
    # pool of 32 unique txns streamed 3x over -> dedup keeps 32
    pipe = build_leader_pipeline(
        n_verify=1, pool_size=32, gen_limit=96, batch=64, max_msg_len=256
    )
    try:
        pipe.run(until_txns=32, max_iters=200_000)
        report = pipe.report()
        # verify's tiny tcache (depth 16) can't hold 32 txns, so dups reach
        # dedup; between the two tcaches all 64 dups die.
        dups = report["verify0"].get("dedup_dup", 0) + report["dedup"].get(
            "dedup_dup", 0
        )
        assert report["pack"]["txn_in"] == 32
        assert dups == 64
    finally:
        pipe.close()


def test_two_way_verify_fanout():
    pipe = build_leader_pipeline(
        n_verify=2, pool_size=64, gen_limit=64, batch=32, max_msg_len=256
    )
    try:
        pipe.run(until_txns=64, max_iters=200_000)
        report = pipe.report()
        v0 = report["verify0"]["txn_verified"]
        v1 = report["verify1"]["txn_verified"]
        assert v0 + v1 == 64
        assert v0 == 32 and v1 == 32  # strict round-robin by seq
        assert report["pack"]["txn_in"] == 64
    finally:
        pipe.close()


def test_corrupted_txn_dropped_by_kernel():
    from firedancer_tpu.runtime.benchg import gen_transfer_pool
    from firedancer_tpu.models import leader as ml

    pool = gen_transfer_pool(16)
    # corrupt one signature byte of txn 5: parses fine, fails sigverify
    bad = bytearray(pool[5])
    bad[10] ^= 0xFF
    pool[5] = bytes(bad)
    # and truncate txn 9: fails parse
    pool[9] = pool[9][:-3]

    pipe = ml.build_leader_pipeline(
        n_verify=1, pool_size=16, gen_limit=16, batch=32, max_msg_len=256
    )
    pipe.benchg.pool = pool
    try:
        pipe.run(until_txns=14, max_iters=200_000)
        report = pipe.report()
        assert report["verify0"]["parse_fail"] == 1
        assert report["verify0"]["verify_fail"] == 1
        assert report["verify0"]["txn_verified"] == 14
        assert report["pack"]["txn_in"] == 14
    finally:
        pipe.close()


def test_encode_decode_verified_roundtrip():
    from firedancer_tpu.runtime.benchg import gen_transfer_pool

    p = gen_transfer_pool(1)[0]
    t = ft.txn_parse(p)
    frag = encode_verified(p, t)
    p2, t2 = decode_verified(frag)
    assert p2 == p and t2 == t
