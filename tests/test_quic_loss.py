"""QUIC loss recovery + flow control (the r3 gap: 'dies on first lost
packet').

Connection-level: handshake + delivery across a deterministic lossy pipe
(every Nth datagram dropped, both directions), driven by explicit
timestamps so PTO firing is exact.  Stage-level: the full ingress e2e
over a 10% drop link lives in test_net_loss.py (socket machinery)."""

import hashlib

import pytest

pytestmark = pytest.mark.slow  # XLA-compile/socket-heavy tier (see conftest)

from firedancer_tpu.waltz import quic
from firedancer_tpu.ops.ref import ed25519_ref as ref


IDENTITY = hashlib.sha256(b"loss-id").digest()


def test_decode_pn_appendix_a3():
    # RFC 9000 A.3 worked example: largest=0xa82f30ea, 16-bit 0x9b32
    assert quic.decode_pn(0x9B32, 16, 0xA82F30EA) == 0xA82F9B32
    # wrap down
    assert quic.decode_pn(0x0001, 16, 0xFFFF) == 0x10001
    # small values stay small
    assert quic.decode_pn(5, 16, 3) == 5
    assert quic.decode_pn(2, 16, -1) == 2


def test_recv_tracker_ranges_and_ack_roundtrip():
    t = quic._RecvTracker()
    for pn in (0, 1, 2, 5, 7, 8, 3):
        t.add(pn)
    assert t.ranges == [[0, 3], [5, 5], [7, 8]]
    assert t.largest == 8
    assert t.seen(2) and t.seen(5) and not t.seen(4)
    wire = quic.ack_frame([tuple(r) for r in t.ranges])
    evs = list(quic.parse_frames(wire))
    assert len(evs) == 1 and evs[0][0] == "ack"
    assert sorted(evs[0][1]) == [(0, 3), (5, 5), (7, 8)]


class LossyPair:
    """Two connections joined by a drop-every-Nth pipe, manual clock."""

    def __init__(self, drop_every: int, *, expected_peer=None):
        self.client = quic.Connection.client_new(expected_peer=expected_peer)
        self.server = quic.Connection.server_new(IDENTITY)
        self.drop_every = drop_every
        self.n = 0
        self.now = 0.0
        self.events = []  # server-side stream events

    def _deliver(self, dg: bytes, dst) -> None:
        self.n += 1
        if self.drop_every and self.n % self.drop_every == 0:
            return  # eaten by the network
        evs = dst.receive(dg, now=self.now)
        if dst is self.server:
            self.events.extend(self.server.receive_stream_events(evs))
        else:
            dst.receive_stream_events(evs)

    def tick(self, dt: float = 0.25) -> None:
        self.now += dt
        for side, peer in ((self.client, self.server),
                           (self.server, self.client)):
            side.poll_timers(self.now)
            for dg in side.flush(self.now):
                self._deliver(dg, peer)

    def run_until(self, cond, max_ticks: int = 200) -> None:
        for _ in range(max_ticks):
            if cond():
                return
            self.tick()
        raise AssertionError("condition not reached under loss")


@pytest.mark.parametrize("drop_every", [3, 4, 7])
def test_handshake_completes_under_loss(drop_every):
    p = LossyPair(drop_every, expected_peer=ref.public_key(IDENTITY))
    p.run_until(lambda: p.client.established and p.server.established)


def test_txns_deliver_under_loss():
    p = LossyPair(4)
    p.run_until(lambda: p.client.established and p.server.established)
    payloads = [b"txn-%02d-" % i + bytes(range(i, i + 40)) for i in range(12)]
    for i, txn in enumerate(payloads):
        p.client.send_stream(2 + 4 * i, txn, fin=True)
    done = {}

    def finished():
        for sid, chunk, fin in p.events:
            done.setdefault(sid, bytearray()).extend(chunk)
        p.events.clear()
        return len(done) == 12 and all(
            p.server.stream_rx[sid].finished for sid in done
        )

    p.run_until(finished)
    got = {bytes(v) for v in done.values()}
    assert got == set(payloads)
    # and the client eventually sees everything acked (no zombie rtx)
    p.run_until(lambda: not p.client.has_unacked())


def test_pto_retransmits_without_acks():
    """A flight into a black hole must retransmit on the PTO schedule."""
    client = quic.Connection.client_new()
    dgs = client.flush(0.0)
    assert dgs  # the padded Initial
    client.poll_timers(0.1)
    assert client.flush(0.1) == []  # before PTO: silence
    client.poll_timers(0.25)       # past the 0.2s initial PTO
    rtx = client.flush(0.25)
    assert rtx, "PTO must retransmit the Initial flight"
    # backoff doubles: next at +0.4, not +0.2
    client.poll_timers(0.5)
    assert client.flush(0.5) == []
    client.poll_timers(0.7)
    assert client.flush(0.7)


def test_ping_only_packet_gets_acked():
    """PING is ack-eliciting: a PTO probe must draw an ACK or the peer
    backs off into an idle timeout (review finding r4)."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    p.tick(); p.tick()  # drain pending acks both ways
    keys = p.client.keys_tx[quic.APPLICATION]
    pn = p.client.pn_next[quic.APPLICATION]
    p.client.pn_next[quic.APPLICATION] += 1
    pkt = quic.seal_packet(
        keys, level=quic.APPLICATION, dcid=p.server.local_cid,
        scid=p.client.local_cid, pn=pn,
        payload=bytes([quic.FT_PING]) + bytes(3),
    )
    p.server.receive(pkt, now=p.now)
    assert quic.APPLICATION in p.server.ack_pending
    assert p.server.flush(p.now)  # the ACK goes out


def test_blocked_stream_writes_keep_order():
    """A later small write must not overtake an earlier blocked write on
    the same stream (review finding r4)."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    c = p.client
    c.tx_stream_limit[2] = 100
    c.send_stream(2, bytes(range(80)), fin=False)   # fits (offset 0..80)
    c.send_stream(2, bytes(range(80, 160)), fin=False)  # blocked (>100)
    c.send_stream(2, bytes(range(160, 170)), fin=True)  # would fit alone
    assert len(c.blocked_out) == 2  # the small write queued BEHIND
    # open the window: everything flows in offset order
    c.tx_stream_limit[2] = 10_000
    c._drain_blocked()
    offs = [item[2] for item in c.app_out if item[1] == 2]
    assert offs == sorted(offs)
    for dg in c.flush(p.now):
        evs = p.server.receive(dg, now=p.now)
        p.events.extend(p.server.receive_stream_events(evs))
    data = bytearray()
    for _sid, chunk, _fin in p.events:
        data.extend(chunk)
    assert bytes(data) == bytes(range(170))


def test_rx_flow_control_enforced():
    """A peer pushing past our advertised stream window is a conn error."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    big = bytes(quic.DEFAULT_MAX_STREAM_DATA + 1)
    ev = quic.StreamEvent(2, 0, big, False)
    with pytest.raises(quic.QuicError, match="flow control"):
        p.server._rx_flow_check(ev)


def test_tx_respects_peer_window_and_unblocks():
    """Writes past the peer's window queue; MAX_DATA releases them."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    c = p.client
    c.tx_max_data = 100  # shrink for the test
    c.send_stream(2, bytes(80), fin=False)
    c.send_stream(6, bytes(50), fin=True)  # would exceed 100 total
    assert len(c.blocked_out) == 1
    assert c.tx_data_total == 80
    wire = bytes([quic.FT_MAX_DATA]) + quic.varint_encode(1000)
    # hand-deliver a MAX_DATA frame through the real path
    keys = p.server.keys_tx[quic.APPLICATION]
    pkt = quic.seal_packet(
        keys, level=quic.APPLICATION, dcid=c.local_cid,
        scid=p.server.local_cid, pn=p.server.pn_next[quic.APPLICATION],
        payload=wire,
    )
    p.server.pn_next[quic.APPLICATION] += 1
    c.receive(pkt, now=p.now)
    assert not c.blocked_out
    assert c.tx_data_total == 130


def test_lost_max_data_retransmits_no_deadlock():
    """Review finding r4: a dropped MAX_DATA must be retransmitted (raw
    ctrl frames are loss-tracked), or the sender deadlocks in
    blocked_out forever."""
    p = LossyPair(3)  # every 3rd datagram dropped
    p.run_until(lambda: p.client.established and p.server.established)
    # shrink both sides' view of the connection window to force updates
    p.client.tx_max_data = 4096
    p.server.rx_max_data = 4096
    total = 0
    sid = 2
    for i in range(12):  # 12 KiB >> the 4 KiB window
        p.client.send_stream(sid + 4 * i, bytes(1024), fin=True)
        total += 1024

    def all_delivered():
        for _sid, chunk, _fin in p.events:
            pass
        return p.server.rx_consumed >= total

    p.run_until(all_delivered, max_ticks=400)
    p.run_until(lambda: not p.client.blocked_out, max_ticks=400)


def test_window_updates_flow_back():
    """Consuming over half the connection window emits MAX_DATA."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    chunk = bytes(1 << 16)
    sid = 2
    sent = 0
    # stream cap is 256K; spread across streams to hit the 1M conn window
    while p.server.rx_consumed * 2 <= quic.DEFAULT_MAX_DATA:
        p.client.send_stream(sid, chunk, fin=False)
        sent += len(chunk)
        if p.client.send_offset[sid] + len(chunk) > (
            quic.DEFAULT_MAX_STREAM_DATA
        ):
            sid += 4
        p.tick(0.01)
    # the server must have queued/sent a MAX_DATA raising the window
    assert p.server.rx_max_data > quic.DEFAULT_MAX_DATA
    # and the client's view of the connection window moved up with it
    p.tick(0.01)
    assert p.client.tx_max_data > quic.DEFAULT_MAX_DATA
