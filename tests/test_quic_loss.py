"""QUIC loss recovery + flow control (the r3 gap: 'dies on first lost
packet').

Connection-level: handshake + delivery across a deterministic lossy pipe
(every Nth datagram dropped, both directions), driven by explicit
timestamps so PTO firing is exact.  Stage-level: the full ingress e2e
over a 10% drop link lives in test_net_loss.py (socket machinery)."""

import hashlib

import pytest

pytestmark = pytest.mark.slow  # XLA-compile/socket-heavy tier (see conftest)

from firedancer_tpu.waltz import quic
from firedancer_tpu.ops.ref import ed25519_ref as ref


IDENTITY = hashlib.sha256(b"loss-id").digest()


def test_decode_pn_appendix_a3():
    # RFC 9000 A.3 worked example: largest=0xa82f30ea, 16-bit 0x9b32
    assert quic.decode_pn(0x9B32, 16, 0xA82F30EA) == 0xA82F9B32
    # wrap down
    assert quic.decode_pn(0x0001, 16, 0xFFFF) == 0x10001
    # small values stay small
    assert quic.decode_pn(5, 16, 3) == 5
    assert quic.decode_pn(2, 16, -1) == 2


def test_recv_tracker_ranges_and_ack_roundtrip():
    t = quic._RecvTracker()
    for pn in (0, 1, 2, 5, 7, 8, 3):
        t.add(pn)
    assert t.ranges == [[0, 3], [5, 5], [7, 8]]
    assert t.largest == 8
    assert t.seen(2) and t.seen(5) and not t.seen(4)
    wire = quic.ack_frame([tuple(r) for r in t.ranges])
    evs = list(quic.parse_frames(wire))
    assert len(evs) == 1 and evs[0][0] == "ack"
    assert sorted(evs[0][1]) == [(0, 3), (5, 5), (7, 8)]


class LossyPair:
    """Two connections joined by a drop-every-Nth pipe, manual clock."""

    def __init__(self, drop_every: int, *, expected_peer=None):
        self.client = quic.Connection.client_new(expected_peer=expected_peer)
        self.server = quic.Connection.server_new(IDENTITY)
        self.drop_every = drop_every
        self.n = 0
        self.now = 0.0
        self.events = []  # server-side stream events

    def _deliver(self, dg: bytes, dst) -> None:
        self.n += 1
        if self.drop_every and self.n % self.drop_every == 0:
            return  # eaten by the network
        evs = dst.receive(dg, now=self.now)
        if dst is self.server:
            self.events.extend(self.server.receive_stream_events(evs))
        else:
            dst.receive_stream_events(evs)

    def tick(self, dt: float = 0.25) -> None:
        self.now += dt
        for side, peer in ((self.client, self.server),
                           (self.server, self.client)):
            side.poll_timers(self.now)
            for dg in side.flush(self.now):
                self._deliver(dg, peer)

    def run_until(self, cond, max_ticks: int = 200) -> None:
        for _ in range(max_ticks):
            if cond():
                return
            self.tick()
        raise AssertionError("condition not reached under loss")


@pytest.mark.parametrize("drop_every", [3, 4, 7])
def test_handshake_completes_under_loss(drop_every):
    p = LossyPair(drop_every, expected_peer=ref.public_key(IDENTITY))
    p.run_until(lambda: p.client.established and p.server.established)


def test_txns_deliver_under_loss():
    p = LossyPair(4)
    p.run_until(lambda: p.client.established and p.server.established)
    payloads = [b"txn-%02d-" % i + bytes(range(i, i + 40)) for i in range(12)]
    for i, txn in enumerate(payloads):
        p.client.send_stream(2 + 4 * i, txn, fin=True)
    done = {}

    def finished():
        for sid, chunk, fin in p.events:
            done.setdefault(sid, bytearray()).extend(chunk)
        p.events.clear()
        return len(done) == 12 and all(
            p.server.stream_rx[sid].finished for sid in done
        )

    p.run_until(finished)
    got = {bytes(v) for v in done.values()}
    assert got == set(payloads)
    # and the client eventually sees everything acked (no zombie rtx)
    p.run_until(lambda: not p.client.has_unacked())


def test_pto_retransmits_without_acks():
    """A flight into a black hole must retransmit on the PTO schedule."""
    client = quic.Connection.client_new()
    dgs = client.flush(0.0)
    assert dgs  # the padded Initial
    client.poll_timers(0.1)
    assert client.flush(0.1) == []  # before PTO: silence
    client.poll_timers(0.25)       # past the 0.2s initial PTO
    rtx = client.flush(0.25)
    assert rtx, "PTO must retransmit the Initial flight"
    # backoff doubles: next at +0.4, not +0.2
    client.poll_timers(0.5)
    assert client.flush(0.5) == []
    client.poll_timers(0.7)
    assert client.flush(0.7)


def test_rtt_estimator_feeds_adaptive_pto():
    """ISSUE 7 satellite: once acks flow, the PTO tracks the measured
    path (srtt + 4*rttvar) instead of the fixed 0.2 s profile."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    # the manual clock ticks 0.25s per exchange (the client's acks land
    # same-tick, so its samples are 0.0 and the granularity floor rules):
    # both endpoints measured the path and run the adaptive interval
    for side in (p.client, p.server):
        assert side.srtt is not None
        assert side.min_rtt is not None and side.min_rtt <= side.srtt
        # adaptive interval: srtt + max(4*rttvar, granularity), no backoff
        assert side.pto_count == 0
        expect = max(
            side.srtt + max(4 * side.rttvar, quic.PTO_GRANULARITY_S),
            quic.PTO_GRANULARITY_S,
        )
        assert side.pto_interval() == pytest.approx(expect)
    assert p.server.srtt > 0  # the cross-tick direction took real samples
    p.tick(); p.tick()  # drain pending acks so flush below is app-only
    # a black-holed flight now retransmits on the ADAPTIVE schedule
    c = p.client
    c.send_stream(2, b"adaptive-pto", fin=True)
    assert c.flush(p.now)  # into the void (server never ticks)
    pto = c.pto_interval()
    c.poll_timers(p.now + pto * 0.5)
    assert c.flush(p.now + pto * 0.5) == []  # before the timer: silence
    c.poll_timers(p.now + pto + 1e-6)
    assert c.flush(p.now + pto + 1e-6), "adaptive PTO must retransmit"
    assert c.pto_count == 1  # and back off


def test_pto_before_first_sample_uses_initial():
    c = quic.Connection.client_new()
    assert c.srtt is None
    assert c.pto_interval() == quic.PTO_INITIAL_S


def test_ack_only_packets_never_arm_pto():
    """Pure-ACK packets are not ack-eliciting: they are never tracked,
    so an endpoint with only ACKs in flight must not retransmit them on
    a timer (an ACK loop would never converge)."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    p.tick(); p.tick()  # drain pending acks both ways
    # hand the server a PING so it owes exactly one ACK
    keys = p.client.keys_tx[quic.APPLICATION]
    pn = p.client.pn_next[quic.APPLICATION]
    p.client.pn_next[quic.APPLICATION] += 1
    pkt = quic.seal_packet(
        keys, level=quic.APPLICATION, dcid=p.server.local_cid,
        scid=p.client.local_cid, pn=pn,
        payload=bytes([quic.FT_PING]) + bytes(3),
    )
    p.server.receive(pkt, now=p.now)
    before = dict(p.server.sent[quic.APPLICATION])
    assert p.server.flush(p.now)  # the ACK-only packet goes out
    # nothing new tracked -> a later PTO poll re-queues nothing
    assert p.server.sent[quic.APPLICATION] == before
    p.server.poll_timers(p.now + 100.0)
    assert p.server.flush(p.now + 100.0) == []


def test_time_threshold_loss_beats_full_pto():
    """A small-gap loss (behind the largest acked by < the packet
    threshold) is declared lost once it ages past 9/8 * rtt — without
    waiting for the much longer PTO (§6.1.2)."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    p.tick(); p.tick()  # drain handshake-tail acks both ways
    c = p.client
    t0 = p.now
    # two separate flushes -> two distinct app packets A (lost) and B
    c.send_stream(2, b"packet-A", fin=True)
    dgs_a = c.flush(t0)
    assert len(dgs_a) == 1
    c.send_stream(6, b"packet-B", fin=True)
    dgs_b = c.flush(t0)
    assert len(dgs_b) == 1
    pn_a, pn_b = sorted(c.sent[quic.APPLICATION])
    # B arrives, A vanished; the server acks B promptly
    evs = p.server.receive(dgs_b[0], now=t0 + 0.25)
    p.server.receive_stream_events(evs)
    ack1 = p.server.flush(t0 + 0.25)
    for dg in ack1:
        c.receive(dg, now=t0 + 0.25)
    assert pn_a in c.sent[quic.APPLICATION]  # gap of 1 < packet threshold
    rtt = c.latest_rtt
    assert rtt is not None
    # a later ack (PING-elicited) re-covering B arrives after A aged past
    # the time threshold: A is declared lost on THAT ack, not at full PTO
    later = t0 + max(9 / 8 * max(c.srtt, rtt), quic.PTO_GRANULARITY_S) + 0.01
    assert later - t0 < c.pto_interval() + 0.25  # the point of the test
    wire = quic.ack_frame([(pn_b, pn_b)])
    keys = p.server.keys_tx[quic.APPLICATION]
    pkt = quic.seal_packet(
        keys, level=quic.APPLICATION, dcid=c.local_cid,
        scid=p.server.local_cid, pn=p.server.pn_next[quic.APPLICATION],
        payload=wire,
    )
    p.server.pn_next[quic.APPLICATION] += 1
    c.receive(pkt, now=later)
    assert pn_a not in c.sent[quic.APPLICATION], "time-threshold missed"
    assert c.stream_rtx, "lost stream data must be queued for rtx"


def test_ping_only_packet_gets_acked():
    """PING is ack-eliciting: a PTO probe must draw an ACK or the peer
    backs off into an idle timeout (review finding r4)."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    p.tick(); p.tick()  # drain pending acks both ways
    keys = p.client.keys_tx[quic.APPLICATION]
    pn = p.client.pn_next[quic.APPLICATION]
    p.client.pn_next[quic.APPLICATION] += 1
    pkt = quic.seal_packet(
        keys, level=quic.APPLICATION, dcid=p.server.local_cid,
        scid=p.client.local_cid, pn=pn,
        payload=bytes([quic.FT_PING]) + bytes(3),
    )
    p.server.receive(pkt, now=p.now)
    assert quic.APPLICATION in p.server.ack_pending
    assert p.server.flush(p.now)  # the ACK goes out


def test_blocked_stream_writes_keep_order():
    """A later small write must not overtake an earlier blocked write on
    the same stream (review finding r4)."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    c = p.client
    c.tx_stream_limit[2] = 100
    c.send_stream(2, bytes(range(80)), fin=False)   # fits (offset 0..80)
    c.send_stream(2, bytes(range(80, 160)), fin=False)  # blocked (>100)
    c.send_stream(2, bytes(range(160, 170)), fin=True)  # would fit alone
    assert len(c.blocked_out) == 2  # the small write queued BEHIND
    # open the window: everything flows in offset order
    c.tx_stream_limit[2] = 10_000
    c._drain_blocked()
    offs = [item[2] for item in c.app_out if item[1] == 2]
    assert offs == sorted(offs)
    for dg in c.flush(p.now):
        evs = p.server.receive(dg, now=p.now)
        p.events.extend(p.server.receive_stream_events(evs))
    data = bytearray()
    for _sid, chunk, _fin in p.events:
        data.extend(chunk)
    assert bytes(data) == bytes(range(170))


def test_rx_flow_control_enforced():
    """A peer pushing past our advertised stream window is a conn error."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    big = bytes(quic.DEFAULT_MAX_STREAM_DATA + 1)
    ev = quic.StreamEvent(2, 0, big, False)
    with pytest.raises(quic.QuicError, match="flow control"):
        p.server._rx_flow_check(ev)


def test_tx_respects_peer_window_and_unblocks():
    """Writes past the peer's window queue; MAX_DATA releases them."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    c = p.client
    c.tx_max_data = 100  # shrink for the test
    c.send_stream(2, bytes(80), fin=False)
    c.send_stream(6, bytes(50), fin=True)  # would exceed 100 total
    assert len(c.blocked_out) == 1
    assert c.tx_data_total == 80
    wire = bytes([quic.FT_MAX_DATA]) + quic.varint_encode(1000)
    # hand-deliver a MAX_DATA frame through the real path
    keys = p.server.keys_tx[quic.APPLICATION]
    pkt = quic.seal_packet(
        keys, level=quic.APPLICATION, dcid=c.local_cid,
        scid=p.server.local_cid, pn=p.server.pn_next[quic.APPLICATION],
        payload=wire,
    )
    p.server.pn_next[quic.APPLICATION] += 1
    c.receive(pkt, now=p.now)
    assert not c.blocked_out
    assert c.tx_data_total == 130


def test_lost_max_data_retransmits_no_deadlock():
    """Review finding r4: a dropped MAX_DATA must be retransmitted (raw
    ctrl frames are loss-tracked), or the sender deadlocks in
    blocked_out forever."""
    p = LossyPair(3)  # every 3rd datagram dropped
    p.run_until(lambda: p.client.established and p.server.established)
    # shrink both sides' view of the connection window to force updates
    p.client.tx_max_data = 4096
    p.server.rx_max_data = 4096
    total = 0
    sid = 2
    for i in range(12):  # 12 KiB >> the 4 KiB window
        p.client.send_stream(sid + 4 * i, bytes(1024), fin=True)
        total += 1024

    def all_delivered():
        for _sid, chunk, _fin in p.events:
            pass
        return p.server.rx_consumed >= total

    p.run_until(all_delivered, max_ticks=400)
    p.run_until(lambda: not p.client.blocked_out, max_ticks=400)


def test_window_updates_flow_back():
    """Consuming over half the connection window emits MAX_DATA."""
    p = LossyPair(0)
    p.run_until(lambda: p.client.established and p.server.established)
    chunk = bytes(1 << 16)
    sid = 2
    sent = 0
    # stream cap is 256K; spread across streams to hit the 1M conn window
    while p.server.rx_consumed * 2 <= quic.DEFAULT_MAX_DATA:
        p.client.send_stream(sid, chunk, fin=False)
        sent += len(chunk)
        if p.client.send_offset[sid] + len(chunk) > (
            quic.DEFAULT_MAX_STREAM_DATA
        ):
            sid += 4
        p.tick(0.01)
    # the server must have queued/sent a MAX_DATA raising the window
    assert p.server.rx_max_data > quic.DEFAULT_MAX_DATA
    # and the client's view of the connection window moved up with it
    p.tick(0.01)
    assert p.client.tx_max_data > quic.DEFAULT_MAX_DATA
