"""Batched SHA-512 vs hashlib, mixed lengths in one batch."""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from firedancer_tpu.ops import sha512 as fsha


def test_sha512_mixed_lengths(rng):
    max_len = 300
    lengths = [0, 1, 111, 112, 127, 128, 129, 239, 240, 255, 256, 300] + list(
        rng.integers(0, max_len + 1, size=4)
    )
    msgs = [rng.bytes(int(n)) for n in lengths]
    buf = np.zeros((max_len, len(msgs)), dtype=np.int32)
    for i, m in enumerate(msgs):
        buf[: len(m), i] = np.frombuffer(m, dtype=np.uint8)
    out = np.asarray(
        jax.jit(lambda b, n: fsha.sha512_msg(b, n, max_len))(
            jnp.asarray(buf), jnp.asarray([len(m) for m in msgs], dtype=jnp.int32)
        )
    )
    for i, m in enumerate(msgs):
        expect = np.frombuffer(hashlib.sha512(m).digest(), dtype=np.uint8)
        assert (out[:, i] == expect).all(), f"len={len(m)}"


def test_sha512_empty_vector():
    out = np.asarray(
        jax.jit(lambda b, n: fsha.sha512_msg(b, n, 8))(
            jnp.zeros((8, 1), dtype=jnp.int32), jnp.zeros(1, dtype=jnp.int32)
        )
    )
    assert bytes(out[:, 0].astype(np.uint8)) == hashlib.sha512(b"").digest()
