"""configure check/init host stages (fdctl configure parity)."""

from firedancer_tpu.utils import hostcfg


def test_all_checks_return_results():
    res = hostcfg.run("check")
    stages = {r.stage for r in res}
    assert {"shm", "nofile", "cpus", "thp", "clocksource",
            "swap"} <= stages
    for r in res:
        assert r.status in (hostcfg.OK, hostcfg.WARN, hostcfg.FAIL)
        assert r.detail
        if r.status != hostcfg.OK:
            assert r.remedy  # every failure names its fix


def test_init_raises_nofile_soft_limit():
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    try:
        if hard >= 4096:
            resource.setrlimit(resource.RLIMIT_NOFILE, (1024, hard))
            res = {r.stage: r for r in hostcfg.run("init")}
            assert res["nofile"].status == hostcfg.OK
            got, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
            assert got >= 4096
    finally:
        resource.setrlimit(resource.RLIMIT_NOFILE, (soft, hard))


def test_configure_cli(capsys):
    from firedancer_tpu.__main__ import main

    rc = main(["configure", "check"])
    out = capsys.readouterr().out
    assert "shm" in out and rc in (0, 1)


def test_compile_cache_partitioned_by_configuration(monkeypatch):
    """AOT entries from different XLA configurations must never share a
    directory (mixed entries segfault at cache load)."""
    from firedancer_tpu.utils import platform as P

    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
    a = P.default_cache_dir()
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=1")
    b = P.default_cache_dir()
    assert a != b
    assert a.startswith(str(P.default_cache_dir().rsplit("/", 1)[0]).rsplit("/", 1)[0])
