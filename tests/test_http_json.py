"""Own HTTP/1.1 parser + JSON lexer (ballet/http, ballet/json
counterparts) and the VM sysvar/return-data syscalls."""

import pytest

from firedancer_tpu.protocol import http as H
from firedancer_tpu.protocol import jsonlex as J


# -- http ---------------------------------------------------------------------


def test_request_parse_incremental():
    raw = b"GET /metrics HTTP/1.1\r\nHost: x\r\nAccept: */*\r\n\r\nBODY"
    assert H.parse_request(raw[:20]) is H.NEED_MORE
    req = H.parse_request(raw)
    assert (req.method, req.path, req.version) == ("GET", "/metrics",
                                                   "HTTP/1.1")
    assert req.header("host") == "x" and req.header("HOST") == "x"
    assert raw[req.head_len :] == b"BODY"


def test_request_malformed():
    with pytest.raises(H.HttpError, match="request line"):
        H.parse_request(b"GARBAGE\r\n\r\n")
    with pytest.raises(H.HttpError, match="version"):
        H.parse_request(b"GET / SPDY/9\r\n\r\n")
    with pytest.raises(H.HttpError, match="header name"):
        H.parse_request(b"GET / HTTP/1.1\r\nBad Header: x\r\n\r\n")
    with pytest.raises(H.HttpError, match="too large"):
        H.parse_request(b"GET / HTTP/1.1\r\nA: " + b"x" * H.MAX_HEAD)


def test_response_and_body_framing():
    raw = (b"HTTP/1.1 200 OK\r\ncontent-length: 5\r\n"
           b"content-type: application/json\r\n\r\nhello")
    res = H.parse_response(raw)
    assert res.status == 200 and res.reason == "OK"
    assert H.body_length(res) == 5
    # chunked
    res2 = H.parse_response(
        b"HTTP/1.1 200 OK\r\ntransfer-encoding: chunked\r\n\r\n"
    )
    assert H.body_length(res2) == "chunked"
    body = b"4\r\nWiki\r\n5\r\npedia\r\n0\r\n\r\n"
    assert H.decode_chunked(body) == (b"Wikipedia", len(body))
    assert H.decode_chunked(body[:10]) is H.NEED_MORE
    with pytest.raises(H.HttpError, match="chunk size"):
        H.decode_chunked(b"zz\r\n")


def test_build_response_roundtrip():
    out = H.build_response(200, b'{"ok":1}', content_type="application/json")
    res = H.parse_response(out)
    assert res.status == 200
    assert H.body_length(res) == 8
    assert out[res.head_len :] == b'{"ok":1}'


# -- json ---------------------------------------------------------------------


def test_json_roundtrip_values():
    cases = [
        None, True, False, 0, -1, 123456789012345678901234567890,
        1.5, -0.25, 1e10,
        "", "héllo\n\"quoted\"\\", {"a": [1, {"b": None}]}, [[]], {},
    ]
    for v in cases:
        assert J.loads(J.dumps(v)) == v


def test_json_strictness():
    for bad in ["{", "[1,]", "{\"a\":}", "01", "1.", "+1", "nul",
                '"\\x"', '"unterminated', "[1] extra", '{"a":1 "b":2}',
                '"\\ud800"']:
        with pytest.raises(J.JsonError):
            J.loads(bad)
    with pytest.raises(J.JsonError, match="deep"):
        J.loads("[" * 100 + "]" * 100)
    with pytest.raises(J.JsonError, match="duplicate"):
        J.loads('{"k":1,"k":2}', reject_duplicate_keys=True)
    assert J.loads('{"k":1,"k":2}') == {"k": 2}  # last-wins by default


def test_json_unicode_escapes():
    assert J.loads('"\\u00e9"') == "é"
    assert J.loads('"\\ud83d\\ude00"') == "\U0001F600"  # surrogate pair
    assert J.loads(J.dumps("tab\tnewline\n")) == "tab\tnewline\n"


def test_json_matches_stdlib_on_rpc_shapes():
    import json as stdlib

    doc = ('{"jsonrpc":"2.0","id":7,"method":"getBalance",'
           '"params":["abc",{"commitment":"finalized"}]}')
    assert J.loads(doc) == stdlib.loads(doc)
    enc = J.dumps(J.loads(doc), sort_keys=True)
    assert stdlib.loads(enc) == stdlib.loads(doc)


# -- VM sysvar + return data syscalls -----------------------------------------


def test_vm_sysvar_and_return_data():
    from firedancer_tpu.flamenco import types as T
    from firedancer_tpu.flamenco import vm as fvm
    from tests.test_executor import lddw
    from tests.test_sbpf import ins
    from tests.test_vm import run_text

    clock = T.CLOCK.encode(T.Clock(slot=42, epoch=3))
    # program: write clock sysvar to heap? use stack: get_clock([r10-64]);
    # then set_return_data of the first 8 bytes (the slot)
    text = (
        ins(0xBF, dst=1, src=10) + ins(0x07, dst=1, imm=-64)
        + ins(0x85, imm=fvm.SYSCALL_SOL_GET_CLOCK)
        + ins(0xBF, dst=6, src=0)              # save rc
        + ins(0xBF, dst=1, src=10) + ins(0x07, dst=1, imm=-64)
        + ins(0xB7, dst=2, imm=8)
        + ins(0x85, imm=fvm.SYSCALL_SOL_SET_RETURN_DATA)
        + ins(0xBF, dst=0, src=6)
        + ins(0x95)
    )
    m = run_text(text)
    m.sysvars["clock"] = clock
    fvm.register_default_syscalls(m)
    assert m.run() == 0
    assert m.return_data[1] == (42).to_bytes(8, "little")

    # without the sysvar provided, the getter reports failure
    m2 = run_text(text)
    fvm.register_default_syscalls(m2)
    assert m2.run() == 1
