"""QUIC hardening: Retry address validation (RFC 9000 §8.1/§17.2.5),
version negotiation (§6), stateless reset (§10.3), and the 3x
anti-amplification budget — the fd_quic.c retry-path capabilities."""

import hashlib
import os
import socket
import struct
import threading
import time

import pytest

from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.tango import shm
from firedancer_tpu.waltz import quic

IDENTITY = hashlib.sha256(b"quic-retry-id").digest()


def test_retry_integrity_tag_rfc9001_a4():
    odcid = bytes.fromhex("8394c8f03e515708")
    pkt = bytes.fromhex("ff000000010008f067a5502a4262b5746f6b656e")
    assert quic.retry_integrity_tag(odcid, pkt).hex() == (
        "04a265ba2eff4d829058fb3f0f2496ba")
    dcid, scid, token, _tag = quic.parse_retry(
        pkt + quic.retry_integrity_tag(odcid, pkt))
    assert scid.hex() == "f067a5502a4262b5"
    assert token == b"token"


def test_retry_gate_tokens():
    gate = quic.RetryGate(b"k" * 32, lifetime_s=5)
    tok = gate.make_token(("1.2.3.4", 55), b"ODCID678")
    assert gate.validate(("1.2.3.4", 55), tok) == b"ODCID678"
    # wrong address, tampered token, expiry
    assert gate.validate(("9.9.9.9", 55), tok) is None
    assert gate.validate(("1.2.3.4", 55),
                         tok[:-1] + bytes([tok[-1] ^ 1])) is None
    assert gate.validate(("1.2.3.4", 55), tok,
                         now=time.time() + 10) is None


def test_client_accepts_one_valid_retry_only():
    c = quic.Connection.client_new()
    first_flight = c.flush()
    assert first_flight
    odcid = c.original_dcid
    new_scid = b"S" * 8
    retry = quic.build_retry(odcid=odcid, dcid=c.local_cid,
                             scid=new_scid, token=b"tok-1")
    c.receive(retry)
    assert c.initial_token == b"tok-1"
    assert c.remote_cid == new_scid
    # the re-sent Initial carries the token on the wire
    resent = c.flush()
    assert resent
    peek = quic.peek_initial_token(resent[0])
    assert peek is not None and peek[2] == b"tok-1"
    # a second retry is ignored (§17.2.5)
    retry2 = quic.build_retry(odcid=odcid, dcid=c.local_cid,
                              scid=b"X" * 8, token=b"tok-2")
    c.receive(retry2)
    assert c.initial_token == b"tok-1"
    # a FORGED retry (bad tag) against a fresh client is dropped
    c2 = quic.Connection.client_new()
    c2.flush()
    bad = quic.build_retry(odcid=b"WRONGCID", dcid=c2.local_cid,
                           scid=b"Y" * 8, token=b"evil")
    c2.receive(bad)
    assert c2.initial_token == b""


def test_version_negotiation_closes_client():
    c = quic.Connection.client_new()
    c.flush()
    vn = quic.build_version_negotiation(c.local_cid, c.remote_cid,
                                        versions=(0xBABABABA,))
    assert quic.is_version_negotiation(vn)
    c.receive(vn)
    assert c.closed


def test_stateless_reset_recognized_by_client():
    c = quic.Connection.client_new()
    token = quic.stateless_reset_token(b"srv-static", b"C" * 8)
    c.peer_reset_tokens.add(token)
    c.receive(quic.build_stateless_reset(token))
    assert c.closed


class _FakeSock:
    def __init__(self):
        self.sent = []

    def sendto(self, dg, dst):
        self.sent.append((dg, dst))


def _mk_ingress(**kw):
    from firedancer_tpu.runtime.net import QuicIngressStage

    link = shm.ShmLink.create(
        f"fdtpu_qr_{os.getpid()}_{int(time.monotonic_ns() % 1_000_000)}",
        depth=256, mtu=2048)
    stage = QuicIngressStage("quic", outs=[shm.Producer(link)],
                             identity_secret=IDENTITY, **kw)
    return stage, link


def test_amplification_budget_caps_unvalidated_path():
    stage, link = _mk_ingress()
    try:
        stage.sock.close()
        stage.sock = _FakeSock()
        addr = ("10.0.0.9", 1234)
        stage._addr_budget[addr] = [100, 0]  # peer sent us 100 bytes
        stage._send(b"x" * 250, addr)   # 250 <= 300: goes out
        stage._send(b"y" * 100, addr)   # would exceed 3x: capped
        assert len(stage.sock.sent) == 1
        assert stage.metrics.get("tx_amplification_capped") == 1
        # more bytes from the peer reopen the budget
        stage._addr_budget[addr][0] += 200
        stage._send(b"z" * 100, addr)
        assert len(stage.sock.sent) == 2
    finally:
        link.close()
        link.unlink()


def test_version_negotiation_and_stateless_reset_on_socket():
    stage, link = _mk_ingress()
    cli = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli.settimeout(5)
    try:
        # long header, unknown version, padded to 1200
        pkt = bytearray([0xC0]) + struct.pack(">I", 5)
        pkt += bytes([8]) + b"D" * 8 + bytes([8]) + b"S" * 8
        pkt += bytes(1200 - len(pkt))
        cli.sendto(bytes(pkt), stage.addr)
        for _ in range(100):
            stage.run_once()
            try:
                resp, _ = cli.recvfrom(2048)
                break
            except socket.timeout:
                continue
        assert quic.is_version_negotiation(resp)
        versions = {struct.unpack_from(">I", resp, p)[0]
                    for p in range(7 + resp[5] + resp[6 + resp[5]],
                                   len(resp) - 3, 4)}
        assert quic.QUIC_V1 in versions
        # a tiny unknown-version probe gets NOTHING (anti-amplification)
        cli.sendto(bytes(pkt[:50]), stage.addr)
        for _ in range(20):
            stage.run_once()
        assert stage.metrics.get("version_negotiation_tx") == 1

        # short-header datagram with an unknown CID -> stateless reset
        sr_probe = bytes([0x41]) + b"Q" * 8 + os.urandom(60)
        cli.sendto(sr_probe, stage.addr)
        resp2 = None
        for _ in range(100):
            stage.run_once()
            try:
                resp2, _ = cli.recvfrom(2048)
                break
            except socket.timeout:
                continue
        assert resp2 is not None
        expect = quic.stateless_reset_token(stage._reset_key, b"Q" * 8)
        assert resp2[-16:] == expect
        assert (resp2[0] & 0xC0) == 0x40
    finally:
        cli.close()
        stage.sock.close()
        link.close()
        link.unlink()


@pytest.mark.timeout(300)
def test_handshake_through_retry_gate():
    """With retry=True the first Initial costs only a stateless Retry;
    the tokened re-attempt completes the handshake and ships a txn."""
    from firedancer_tpu.runtime.net import QuicTxnClient

    stage, link = _mk_ingress(retry=True)
    consumer = shm.Consumer(link, lazy=8)
    try:
        box = {}

        def connect():
            box["c"] = QuicTxnClient(
                stage.addr, expected_peer=ref.public_key(IDENTITY),
                timeout_s=60,
            )

        t = threading.Thread(target=connect)
        t.start()
        deadline = time.monotonic() + 120
        while t.is_alive() and time.monotonic() < deadline:
            stage.run_once()
            time.sleep(0.001)
        t.join(timeout=1)
        assert "c" in box, "handshake failed through the retry gate"
        assert stage.metrics.get("retry_tx") >= 1
        assert len(stage.conns) == 1
        # a txn flows end to end
        txn = b"\xabtxn-bytes" * 10
        box["c"].send_txn(txn)
        got = None
        for _ in range(2000):
            stage.run_once()
            frag = consumer.poll()
            if isinstance(frag, tuple):
                got = bytes(frag[1])
                break
            time.sleep(0.001)
        assert got == txn
    finally:
        stage.sock.close()
        link.close()
        link.unlink()
