"""The round-5 syscall completions: blake3/poseidon/big_mod_exp,
bn254 compression, curve25519 group ops (edwards + ristretto),
introspection (stack height, remaining CUs, sibling instructions), and
the fees/epoch-rewards/last-restart-slot sysvar getters — the
fd_vm_syscall_{hash,crypto,curve}.c / fd_vm_syscall.c surface."""

import hashlib

from firedancer_tpu.flamenco import vm as fvm
from firedancer_tpu.protocol import sbpf
from tests.test_sbpf import build_elf, ins

EXIT = ins(0x95)
INP = fvm.MM_INPUT


def mkvm(input_data=b"\x00" * 4096, budget=2_000_000):
    prog = sbpf.load(build_elf(EXIT))
    m = fvm.Vm(prog, input_data=input_data, budget=budget)
    fvm.register_default_syscalls(m)
    return m


def call(vm, sid, *args):
    a = list(args) + [0] * (5 - len(args))
    return vm.syscalls[sid](vm, *a)


def put(vm, off, data):
    vm._write_span(INP + off, data)
    return INP + off


def get(vm, off, n):
    return vm.mem_read_bytes(INP + off, n)


def test_sol_blake3():
    from firedancer_tpu.ops.blake3 import blake3_host

    vm = mkvm()
    msg = b"blake3 syscall"
    data_addr = put(vm, 0, msg)
    # one (addr, len) slice descriptor at offset 100
    put(vm, 100, data_addr.to_bytes(8, "little")
        + len(msg).to_bytes(8, "little"))
    assert call(vm, fvm.SYSCALL_SOL_BLAKE3, INP + 100, 1, INP + 200) == 0
    assert get(vm, 200, 32) == blake3_host(msg)


def test_sol_poseidon_kat():
    vm = mkvm()
    data_addr = put(vm, 0, bytes([1]) * 32)
    put(vm, 100, data_addr.to_bytes(8, "little") + (32).to_bytes(8, "little"))
    # endianness selector 1 = little endian (the KAT's byte order)
    assert call(vm, fvm.SYSCALL_SOL_POSEIDON, 0, 1, INP + 100, 1,
                INP + 200) == 0
    gold = bytes([230, 117, 27, 127, 210, 224, 145, 185, 157, 99, 172, 7,
                  132, 30, 241, 130, 136, 166, 99, 99, 197, 198, 25, 204,
                  119, 97, 238, 129, 229, 172, 191, 5])
    assert get(vm, 200, 32) == gold
    # unknown parameter set rejected
    assert call(vm, fvm.SYSCALL_SOL_POSEIDON, 9, 1, INP + 100, 1,
                INP + 200) == 1


def test_sol_big_mod_exp():
    vm = mkvm()
    base = put(vm, 0, (7).to_bytes(8, "big"))
    exp = put(vm, 16, (5).to_bytes(8, "big"))
    mod = put(vm, 32, (13).to_bytes(8, "big"))
    params = put(vm, 64, b"".join(
        v.to_bytes(8, "little")
        for v in (base, 8, exp, 8, mod, 8)
    ))
    assert call(vm, fvm.SYSCALL_SOL_BIG_MOD_EXP, params, INP + 300) == 0
    assert int.from_bytes(get(vm, 300, 8), "big") == pow(7, 5, 13)
    # zero modulus rejected
    put(vm, 32, bytes(8))
    assert call(vm, fvm.SYSCALL_SOL_BIG_MOD_EXP, params, INP + 300) == 1


def test_sol_alt_bn128_compression_roundtrip():
    from firedancer_tpu.ops import bn254 as bn

    vm = mkvm()
    enc = bn.g1_encode(bn.g1_mul(bn.G1_GEN, 9))
    put(vm, 0, enc)
    assert call(vm, fvm.SYSCALL_SOL_ALT_BN128_COMPRESSION, 0, INP, 64,
                INP + 100) == 0
    comp = get(vm, 100, 32)
    assert comp == bn.g1_compress(enc)
    assert call(vm, fvm.SYSCALL_SOL_ALT_BN128_COMPRESSION, 1, INP + 100,
                32, INP + 200) == 0
    assert get(vm, 200, 64) == enc


def test_curve_validate_point():
    from firedancer_tpu.ops import ristretto as ri
    from firedancer_tpu.ops.ref import ed25519_ref as ed

    vm = mkvm()
    put(vm, 0, ed.point_compress(ed.BASE))
    assert call(vm, fvm.SYSCALL_SOL_CURVE_VALIDATE_POINT,
                fvm.CURVE25519_EDWARDS, INP) == 0
    put(vm, 0, ri.BASE_BYTES)
    assert call(vm, fvm.SYSCALL_SOL_CURVE_VALIDATE_POINT,
                fvm.CURVE25519_RISTRETTO, INP) == 0
    # a negative-s ristretto encoding is invalid
    put(vm, 0, (2**255 - 20).to_bytes(32, "little"))
    assert call(vm, fvm.SYSCALL_SOL_CURVE_VALIDATE_POINT,
                fvm.CURVE25519_RISTRETTO, INP) == 1


def test_curve_group_ops_ristretto():
    """B + B == 2*B through the syscalls, matching RFC 9496's table."""
    from firedancer_tpu.ops import ristretto as ri

    two_b = bytes.fromhex(
        "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919"
    )
    vm = mkvm()
    put(vm, 0, ri.BASE_BYTES)
    put(vm, 32, ri.BASE_BYTES)
    assert call(vm, fvm.SYSCALL_SOL_CURVE_GROUP_OP,
                fvm.CURVE25519_RISTRETTO, fvm.CURVE_OP_ADD,
                INP, INP + 32, INP + 100) == 0
    assert get(vm, 100, 32) == two_b
    # 2*B via scalar mul
    put(vm, 200, (2).to_bytes(32, "little"))
    assert call(vm, fvm.SYSCALL_SOL_CURVE_GROUP_OP,
                fvm.CURVE25519_RISTRETTO, fvm.CURVE_OP_MUL,
                INP + 200, INP, INP + 300) == 0
    assert get(vm, 300, 32) == two_b
    # 2B - B == B
    put(vm, 400, two_b)
    assert call(vm, fvm.SYSCALL_SOL_CURVE_GROUP_OP,
                fvm.CURVE25519_RISTRETTO, fvm.CURVE_OP_SUB,
                INP + 400, INP, INP + 500) == 0
    assert get(vm, 500, 32) == ri.BASE_BYTES


def test_curve_multiscalar_mul():
    """1*B + 2*B == 3*B (RFC 9496 multiple)."""
    from firedancer_tpu.ops import ristretto as ri

    three_b = bytes.fromhex(
        "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259"
    )
    vm = mkvm()
    put(vm, 0, (1).to_bytes(32, "little") + (2).to_bytes(32, "little"))
    put(vm, 100, ri.BASE_BYTES + ri.BASE_BYTES)
    assert call(vm, fvm.SYSCALL_SOL_CURVE_MULTISCALAR_MUL,
                fvm.CURVE25519_RISTRETTO, INP, INP + 100, 2,
                INP + 200) == 0
    assert get(vm, 200, 32) == three_b
    # non-canonical scalar (>= L) rejected
    from firedancer_tpu.ops.ref.ed25519_ref import L

    put(vm, 0, L.to_bytes(32, "little") + (2).to_bytes(32, "little"))
    assert call(vm, fvm.SYSCALL_SOL_CURVE_MULTISCALAR_MUL,
                fvm.CURVE25519_RISTRETTO, INP, INP + 100, 2,
                INP + 200) == 1


def test_introspection_syscalls():
    vm = mkvm()
    vm.stack_height = 3
    assert call(vm, fvm.SYSCALL_SOL_GET_STACK_HEIGHT) == 3
    used = vm.cu_used
    rem = call(vm, fvm.SYSCALL_SOL_REMAINING_CU)
    assert rem == vm.budget - used - fvm.SYSCALL_BASE_COST


def test_sibling_instruction():
    vm = mkvm()
    vm.stack_height = 1
    pid = b"P" * 32
    vm.instr_trace = [
        (1, pid, [(b"A" * 32, True, False)], b"\x01\x02"),
        (2, b"X" * 32, [], b"inner"),  # deeper: not a sibling
        (1, b"Q" * 32, [(b"B" * 32, False, True)], b"\x09"),
    ]
    # index 0 = most recent sibling at height 1 -> the Q instruction;
    # copy happens only with EXACT lengths (data 1, accounts 1)
    put(vm, 0, (1).to_bytes(8, "little") + (1).to_bytes(8, "little"))
    assert call(vm, fvm.SYSCALL_SOL_GET_SIBLING_INSTR, 0, INP, INP + 100,
                INP + 200, INP + 300) == 1
    assert get(vm, 100, 32) == b"Q" * 32
    assert get(vm, 200, 1) == b"\x09"
    acct = get(vm, 300, 34)
    assert acct[:32] == b"B" * 32 and acct[32] == 0 and acct[33] == 1
    # index 1 -> the P instruction; oversized lengths write back the
    # true sizes WITHOUT copying the payload (Agave's equality gate)
    put(vm, 0, (16).to_bytes(8, "little") + (8).to_bytes(8, "little"))
    put(vm, 100, bytes(32))
    assert call(vm, fvm.SYSCALL_SOL_GET_SIBLING_INSTR, 1, INP, INP + 100,
                INP + 200, INP + 300) == 1
    assert get(vm, 100, 32) == bytes(32)  # untouched
    assert int.from_bytes(get(vm, 0, 8), "little") == 2  # true data len
    # exact lengths now copy
    put(vm, 0, (2).to_bytes(8, "little") + (1).to_bytes(8, "little"))
    assert call(vm, fvm.SYSCALL_SOL_GET_SIBLING_INSTR, 1, INP, INP + 100,
                INP + 200, INP + 300) == 1
    assert get(vm, 100, 32) == b"P" * 32
    # index 2: no more siblings
    assert call(vm, fvm.SYSCALL_SOL_GET_SIBLING_INSTR, 2, INP, INP + 100,
                INP + 200, INP + 300) == 0


def test_sibling_search_stops_at_parent_boundary():
    """A deeper instruction must not see another parent's children: the
    backward walk breaks at the first entry shallower than the caller."""
    vm = mkvm()
    vm.stack_height = 2
    vm.instr_trace = [
        (1, b"A" * 32, [], b""),
        (2, b"X" * 32, [], b"childA"),   # A's child
        (1, b"B" * 32, [], b""),         # boundary: B's top-level entry
    ]
    # caller is B's child at height 2: X (A's child) must be INVISIBLE
    put(vm, 0, bytes(16))
    assert call(vm, fvm.SYSCALL_SOL_GET_SIBLING_INSTR, 0, INP, INP + 100,
                INP + 200, INP + 300) == 0


def test_new_sysvar_getters():
    from firedancer_tpu.flamenco.runtime import default_sysvars

    vm = mkvm()
    vm.sysvars = default_sysvars(7)
    assert call(vm, fvm.SYSCALL_SOL_GET_FEES, INP) == 0
    assert int.from_bytes(get(vm, 0, 8), "little") == 5000
    assert call(vm, fvm.SYSCALL_SOL_GET_LAST_RESTART_SLOT, INP + 50) == 0
    assert int.from_bytes(get(vm, 50, 8), "little") == 0
    assert call(vm, fvm.SYSCALL_SOL_GET_EPOCH_REWARDS, INP + 100) == 0
    # the 81-byte EpochRewards blob: active is the LAST byte (offset 80)
    assert get(vm, 100, 81)[80] == 0  # active = false


def test_executor_records_instr_trace():
    """The executor's trace feeds sibling introspection: two top-level
    instructions leave two height-1 entries."""
    import hashlib as hl

    from firedancer_tpu.flamenco.executor import (
        Account, Executor, InstrAccount, TxnCtx,
    )
    from firedancer_tpu.protocol.txn import SYSTEM_PROGRAM

    a = Account(key=hl.sha256(b"ta").digest(), lamports=1000,
                owner=SYSTEM_PROGRAM, executable=False, data=bytearray())
    b = Account(key=hl.sha256(b"tb").digest(), lamports=0,
                owner=SYSTEM_PROGRAM, executable=False, data=bytearray())
    ctx = TxnCtx(accounts=[a, b], signer=[True, False],
                 writable=[True, True])
    ex = Executor()
    data = (2).to_bytes(4, "little") + (5).to_bytes(8, "little")
    for _ in range(2):
        ex.execute_instr(ctx, SYSTEM_PROGRAM,
                         [InstrAccount(0, True, True),
                          InstrAccount(1, False, True)], data)
    assert len(ctx.instr_trace) == 2
    assert all(h == 1 for h, *_ in ctx.instr_trace)
    assert ctx.instr_trace[0][1] == SYSTEM_PROGRAM
