"""The live metrics plane: shm-backed per-stage registries scraped from
an uninvolved process, monitor latency columns, and the crash-surviving
flight recorder (ISSUE 5; the metric tile + fdctl monitor parity pair).

Stage classes and builders are MODULE-LEVEL so they pickle into spawned
children (the same discipline fdlint FD205/FD110 enforce).
"""

import json
import os
import time

from firedancer_tpu.runtime import monitor as mon
from firedancer_tpu.runtime import topo as ft
from firedancer_tpu.runtime.stage import Stage
from firedancer_tpu.tango import shm
from firedancer_tpu.utils import metrics as fm

# CI uploads this as a workflow artifact: the suite's final live-scrape
# snapshot, so a flaky run comes with metric evidence attached
SNAPSHOT_PATH = os.path.join(mon.RUN_DIR, "fdtpu_t1_metrics_snapshot.prom")


class _PingStage(Stage):
    """Publishes `limit` small frags, then idles."""

    def __init__(self, *args, limit=64, **kwargs):
        super().__init__(*args, **kwargs)
        self.limit = limit
        self._sent = 0

    def after_credit(self):
        if self._sent < self.limit:
            if self.publish(0, b"ping" * 8, sig=self._sent):
                self._sent += 1


class _SinkStage(Stage):
    """Consumes frags; the base run loop counts + observes latency."""


class _DoomedStage(Stage):
    """Runs normally, then raises (the induced-FAIL test subject)."""

    def during_housekeeping(self):
        if self._iter > 400:
            raise RuntimeError("induced failure for the flight recorder")


def _ping_builder(links, cnc, *, limit=64):
    return _PingStage("ping", outs=[shm.Producer(links["pc"])], cnc=cnc,
                      limit=limit)


def _sink_builder(links, cnc):
    return _SinkStage("sink", ins=[shm.Consumer(links["pc"], lazy=8)],
                      cnc=cnc)


def _doomed_builder(links, cnc):
    return _DoomedStage("doomed", outs=[shm.Producer(links["nn"])], cnc=cnc,
                        lazy=64)


def _ping_topology(limit=64):
    topo = ft.Topology()
    topo.link("pc", depth=256, mtu=64)
    topo.stage("ping", _ping_builder, limit=limit, outs=["pc"])
    topo.stage("sink", _sink_builder, ins=["pc"])
    return topo


def _wait_for(pred, timeout_s=30.0, poll_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll_s)
    return False


# -- live scrape from a separate process --------------------------------------


def test_live_topology_scrape_and_monitor_latency():
    """The acceptance path: a launched topology exposes per-stage
    counters + nonzero frag_latency_ns histograms, read via the run
    descriptor by a process that did not start any stage."""
    h = ft.launch(_ping_topology(limit=64))
    try:
        ses = mon.MonitorSession.attach(mon.descriptor_path(h.uid))
        try:
            assert ses.wait_ready(timeout_s=30)
            regs = ses.registries()
            assert set(regs) == {"ping", "sink"}

            def sink_counted():
                return regs["sink"].hist("frag_latency_ns")["count"] >= 64

            assert _wait_for(sink_counted), ses.scrape()
            # counters made it across the process boundary
            assert regs["sink"].get("frags_in") >= 64
            assert regs["ping"].get("frags_out") >= 64
            # the exposition format carries the histogram with counts
            text = ses.scrape()
            assert 'frags_in{stage="sink"}' in text
            assert 'frag_latency_ns_bucket{stage="sink"' in text
            count_line = [
                ln for ln in text.splitlines()
                if ln.startswith('frag_latency_ns_count{stage="sink"}')
            ]
            assert count_line and int(count_line[0].split()[-1]) >= 64
            # monitor rows grow the latency percentile columns
            rows = {r["stage"]: r for r in ses.sample()}
            assert rows["sink"]["lat_p50_ms"] is not None
            assert rows["sink"]["lat_p99_ms"] >= rows["sink"]["lat_p50_ms"]
            rendered = mon.MonitorSession.render(list(rows.values()), None,
                                                 1.0)
            assert "p99 lat" in rendered
            # the TUI shows a concrete latency cell, not the "-" blank
            sink_row = [ln for ln in rendered.splitlines()
                        if ln.startswith("sink")][0]
            assert "ms" in sink_row
            # persist the snapshot CI uploads as a workflow artifact
            with open(SNAPSHOT_PATH, "w") as f:
                f.write(text)
        finally:
            regs = rows = None  # drop shm views before the mapping closes
            ses.close()
        h.halt()
    finally:
        h.close()


def test_metrics_cli_once(capsys):
    """`python -m firedancer_tpu metrics --once` — the metric-tile CLI —
    against a live descriptor."""
    from firedancer_tpu.__main__ import main

    h = ft.launch(_ping_topology(limit=32))
    try:
        ses = mon.MonitorSession.attach(mon.descriptor_path(h.uid))
        try:
            assert ses.wait_ready(timeout_s=30)
            assert _wait_for(
                lambda: ses.registries()["sink"].get("frags_in") >= 32
            )
        finally:
            ses.close()
        rc = main(["metrics", "--once",
                   "--descriptor", mon.descriptor_path(h.uid)])
        out = capsys.readouterr().out
        assert rc == 0
        assert 'frags_in{stage="sink"}' in out
        assert "# TYPE frag_latency_ns histogram" in out
        h.halt()
    finally:
        h.close()


def test_metrics_cli_serve_http():
    """--serve binds the metric-tile HTTP endpoint over the attached
    registries (exercised directly via MetricsServer + session)."""
    import urllib.request

    h = ft.launch(_ping_topology(limit=16))
    try:
        ses = mon.MonitorSession.attach(mon.descriptor_path(h.uid))
        try:
            assert ses.wait_ready(timeout_s=30)
            srv = fm.MetricsServer(ses.registries())
            try:
                host, port = srv.addr
                body = urllib.request.urlopen(
                    f"http://{host}:{port}/metrics", timeout=10
                ).read().decode()
                assert 'frags_out{stage="ping"}' in body
            finally:
                srv.close()
                srv.stages = {}  # drop shm views before the mapping closes
        finally:
            ses.close()
        h.halt()
    finally:
        h.close()


# -- flight recorder ----------------------------------------------------------


def test_flight_dump_on_stage_fail_converts_to_chrome_trace(tmp_path):
    """A stage that raises mid-run: the supervisor writes the flight
    dump, and `fdtpu trace` converts it to Chrome trace JSON whose
    schema Perfetto accepts."""
    from firedancer_tpu.__main__ import main

    topo = ft.Topology()
    topo.link("nn", depth=64, mtu=64)
    topo.stage("doomed", _doomed_builder, outs=["nn"])
    topo.stage("sink", _sink_builder_nn, ins=["nn"])
    h = ft.launch(topo)
    try:
        ok = h.supervise(until=lambda hh: False, timeout_s=60,
                         heartbeat_timeout_s=30)
        assert ok is False and h.failed == "doomed"
        dump_path = h.flight_dump_path
        assert dump_path and os.path.exists(dump_path)
        dump = json.load(open(dump_path))
        assert dump["failed"] == "doomed"
        events = [ev for _, ev, _ in dump["stages"]["doomed"]["records"]]
        assert fm.EV_FAIL in events, events
        assert fm.EV_RUN in events
        # the dump carries the final metrics snapshot as evidence
        assert 'frags_out{stage="doomed"}' in dump.get("metrics", "")
        # convert via the CLI and validate the trace-event schema
        out_path = str(tmp_path / "trace.json")
        rc = main(["trace", "--dump", dump_path, "--out", out_path])
        assert rc == 0
        trace = json.load(open(out_path))
        assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
        for ev in trace["traceEvents"]:
            assert ev["ph"] in ("i", "M", "b", "e")
            assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
            if ev["ph"] != "M":
                assert isinstance(ev["ts"], (int, float))
            if ev["ph"] in ("b", "e"):  # async spans need cat + id
                assert ev["cat"] and ev["id"]
        names = {
            ev["args"]["name"] for ev in trace["traceEvents"]
            if ev["ph"] == "M" and ev["name"] == "thread_name"
        }
        assert names == {"doomed", "sink"}
        # every async batch span must open and close exactly once
        opens = [ev["id"] for ev in trace["traceEvents"] if ev["ph"] == "b"]
        closes = [ev["id"] for ev in trace["traceEvents"] if ev["ph"] == "e"]
        assert sorted(opens) == sorted(closes)
        assert len(set(opens)) == len(opens)
    finally:
        # the dump must survive close() — it is the evidence trail
        h.close()
    assert os.path.exists(h.flight_dump_path)
    os.remove(h.flight_dump_path)


def _sink_builder_nn(links, cnc):
    return _SinkStage("sink", ins=[shm.Consumer(links["nn"], lazy=8)],
                      cnc=cnc)


def test_flight_recorder_ring_wrap_and_replay():
    rec = fm.FlightRecorder(capacity=4)
    for k in range(10):
        rec.record(fm.EV_HOUSEKEEPING, k, ts=1000 + k)
    recs = rec.records()
    assert len(recs) == 4
    assert [r[2] for r in recs] == [6, 7, 8, 9]  # oldest-first, last 4
    # replay preserves timestamps into a (larger) shm-side ring
    dst = fm.FlightRecorder(capacity=8)
    rec.replay_into(dst)
    assert [r[0] for r in dst.records()] == [1006, 1007, 1008, 1009]


def test_chrome_trace_pipelined_batches_pair_fifo():
    """Overlapping device batches (max_inflight > 1) complete FIFO; the
    exporter must pair submit k with completion k via async span ids —
    LIFO B/E duration events would swap the spans' durations/args."""
    dump = {
        "uid": "t", "failed": None, "reason": "",
        "stages": {"verify0": {"records": [
            (1000, fm.EV_BATCH_SUBMIT, 11),    # batch 1 submit
            (2000, fm.EV_BATCH_SUBMIT, 22),    # batch 2 submit (overlaps)
            (3000, fm.EV_BATCH_COMPLETE, 11),  # batch 1 completes first
            (4000, fm.EV_BATCH_COMPLETE, 22),
        ]}},
    }
    evs = fm.flight_to_chrome_trace(dump)["traceEvents"]
    spans = {}
    for ev in evs:
        if ev["ph"] in ("b", "e"):
            spans.setdefault(ev["id"], {})[ev["ph"]] = ev
    assert len(spans) == 2
    by_open = sorted(spans.values(), key=lambda s: s["b"]["ts"])
    # batch 1: 1000->3000 us/1e3, elems 11 on both ends; batch 2: 2000->4000
    assert (by_open[0]["b"]["ts"], by_open[0]["e"]["ts"]) == (1.0, 3.0)
    assert by_open[0]["e"]["args"]["elems"] == 11
    assert (by_open[1]["b"]["ts"], by_open[1]["e"]["ts"]) == (2.0, 4.0)
    assert by_open[1]["e"]["args"]["elems"] == 22


def test_trace_cli_live_snapshot(tmp_path):
    """`fdtpu trace` against a LIVE run (no dump): snapshots the rings."""
    from firedancer_tpu.__main__ import main

    h = ft.launch(_ping_topology(limit=8))
    try:
        ses = mon.MonitorSession.attach(mon.descriptor_path(h.uid))
        try:
            assert ses.wait_ready(timeout_s=30)
        finally:
            ses.close()
        out_path = str(tmp_path / "live_trace.json")
        rc = main(["trace", "--descriptor", mon.descriptor_path(h.uid),
                   "--out", out_path])
        assert rc == 0
        trace = json.load(open(out_path))
        assert trace["traceEvents"]
        h.halt()
    finally:
        h.close()


# -- concurrent scrape vs registrar mutation ----------------------------------


def test_metrics_server_concurrent_scrape_and_registration():
    """The snapshot contract at utils/metrics.py MetricsServer: scrapes
    on per-connection threads race a registrar adding stages — every
    scrape must return a coherent exposition, never raise."""
    import threading
    import urllib.request

    schema = fm.MetricsSchema().counter("txn_total").histogram(
        "lat", [1.0, 10.0, 100.0]
    )
    stages = {"stage0": fm.MetricsRegistry(schema)}
    srv = fm.MetricsServer(stages)
    errors = []
    stop = threading.Event()

    def registrar():
        k = 1
        while not stop.is_set():
            reg = fm.MetricsRegistry(schema)
            reg.inc("txn_total", k)
            reg.observe("lat", k % 200)
            srv.stages[f"stage{k}"] = reg
            k += 1
            time.sleep(0.001)

    t = threading.Thread(target=registrar, daemon=True)
    t.start()
    try:
        host, port = srv.addr
        for _ in range(50):
            try:
                body = urllib.request.urlopen(
                    f"http://{host}:{port}/metrics", timeout=10
                ).read().decode()
            except Exception as e:  # any scrape failure is the bug
                errors.append(e)
                break
            assert 'txn_total{stage="stage0"}' in body
    finally:
        stop.set()
        t.join(timeout=5)
        srv.close()
    assert errors == []
    assert len(srv.stages) > 1  # the registrar really was mutating


# -- scraper re-resolution (ISSUE 20 satellite 2) -----------------------------
#
# Two failure modes a long-lived scraper must survive:
#   (a) in-place restart: the supervisor SIGKILLs + respawns a stage
#       against the SAME shm, so served counters continue monotonically;
#   (b) run replacement: a new run takes over the advertised descriptor
#       path, so the scraper must re-resolve the registry set instead of
#       serving the dead run's (stale) counters forever.


def _pong_builder(links, cnc, *, limit=64):
    return _PingStage("pong", outs=[shm.Producer(links["pc"])], cnc=cnc,
                      limit=limit)


def _drain_builder(links, cnc):
    return _SinkStage("drain", ins=[shm.Consumer(links["pc"], lazy=8)],
                      cnc=cnc)


def _pong_topology(limit=64):
    topo = ft.Topology()
    topo.link("pc", depth=256, mtu=64)
    topo.stage("pong", _pong_builder, limit=limit, outs=["pc"])
    topo.stage("drain", _drain_builder, ins=["pc"])
    return topo


def test_monitor_refresh_follows_replaced_run():
    """MonitorSession.refresh(): no-op while the run is unchanged, full
    re-attach when a NEW run takes over the descriptor path."""
    h1 = ft.launch(_ping_topology(limit=16))
    h2 = None
    try:
        path1 = mon.descriptor_path(h1.uid)
        ses = mon.MonitorSession.attach(path1)
        try:
            assert ses.wait_ready(timeout_s=30)
            uid1 = ses.uid
            assert ses.refresh() is False  # same run -> keep mappings
            assert ses.uid == uid1
            h2 = ft.launch(_pong_topology(limit=16))
            # the operator restarted the validator behind the same
            # advertised path: new uid, new stage set, new segments
            with open(mon.descriptor_path(h2.uid)) as f:
                blob = f.read()
            with open(path1, "w") as f:
                f.write(blob)
            assert ses.refresh() is True
            assert ses.uid == h2.uid and ses.uid != uid1
            assert set(ses.registries()) == {"pong", "drain"}
            assert ses.wait_ready(timeout_s=30)
        finally:
            ses.close()
    finally:
        if h2 is not None:
            h2.close()
        h1.close()


def test_metrics_server_resolver_re_resolves_across_run_replacement():
    """The `fdtpu metrics --serve` wiring: a resolver-equipped server
    must serve the NEW run's registries after replacement — never the
    dead run's frozen counters (the stale-scrape regression)."""
    import urllib.request

    h1 = ft.launch(_ping_topology(limit=16))
    h2 = None
    try:
        path1 = mon.descriptor_path(h1.uid)
        ses = mon.MonitorSession.attach(path1)
        try:
            assert ses.wait_ready(timeout_s=30)

            def resolve():
                ses.refresh()
                return ses.registries(), ses.shard_labels()

            srv = fm.MetricsServer(ses.registries(),
                                   labels=ses.shard_labels(),
                                   resolver=resolve)
            try:
                host, port = srv.addr

                def scrape():
                    return urllib.request.urlopen(
                        f"http://{host}:{port}/metrics", timeout=10
                    ).read().decode()

                assert 'stage="ping"' in scrape()
                h2 = ft.launch(_pong_topology(limit=16))
                with open(mon.descriptor_path(h2.uid)) as f:
                    blob = f.read()
                with open(path1, "w") as f:
                    f.write(blob)
                body = scrape()
                assert 'stage="pong"' in body
                assert 'stage="ping"' not in body  # stale set dropped
            finally:
                srv.close()
                srv.stages = {}  # drop shm views before mappings close
        finally:
            ses.close()
    finally:
        if h2 is not None:
            h2.close()
        h1.close()


def test_scrape_continuity_across_in_place_restart():
    """SIGKILL a restartable publisher mid-scrape: the supervisor
    respawns it against the SAME shm metrics segment, so an attached
    HTTP scraper sees counters continue monotonically — no reset, no
    stale plateau, no failed scrapes."""
    import urllib.request

    from firedancer_tpu.runtime.restart import RestartPolicy

    topo = ft.Topology()
    topo.link("pc", depth=256, mtu=64)
    topo.stage("ping", _ping_builder, limit=100_000, outs=["pc"],
               restartable=True)
    topo.stage("sink", _sink_builder, ins=["pc"])
    h = ft.launch(topo)
    ses = None
    srv = None
    try:
        ses = mon.MonitorSession.attach(mon.descriptor_path(h.uid))
        assert ses.wait_ready(timeout_s=30)

        def resolve():
            ses.refresh()
            return ses.registries(), ses.shard_labels()

        srv = fm.MetricsServer(ses.registries(), labels=ses.shard_labels(),
                               resolver=resolve)
        host, port = srv.addr
        seen = []
        killed = [0]
        kill_val = [0]

        def scrape_sink():
            body = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10
            ).read().decode()
            for ln in body.splitlines():
                if ln.startswith('frags_in{stage="sink"}'):
                    return int(ln.split()[-1])
            return None

        def on_poll(hh):
            v = scrape_sink()
            if v is None:
                return
            seen.append(v)
            if v > 200 and killed[0] == 0:
                killed[0] = 1
                kill_val[0] = v
                hh.kill_stage("ping")

        ok = h.supervise(
            until=lambda hh: killed[0] and seen
            and seen[-1] >= kill_val[0] + 300,
            timeout_s=90, on_poll=on_poll,
            restart=RestartPolicy(max_restarts=2, backoff_base_s=0.03,
                                  seed=5))
        assert ok, f"supervise failed (failed={h.failed!r})"
        assert killed[0] == 1 and h.restarts == {"ping": 1}
        # monotonic across the respawn: same segment, counters continue
        assert seen == sorted(seen)
        assert seen[-1] >= kill_val[0] + 300
        h.halt()
    finally:
        if srv is not None:
            srv.close()
            srv.stages = {}
        if ses is not None:
            ses.close()
        h.close()
