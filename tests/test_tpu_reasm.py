"""TPU stream reassembly tests: fragmented txns complete at FIN, slot
stealing under pressure, oversize cancel, interop with the verify parser."""

import pytest

from firedancer_tpu.runtime.benchg import gen_transfer_pool
from firedancer_tpu.runtime.tpu_reasm import TpuReasm
from firedancer_tpu.protocol import txn as ft


def test_fragmented_txn_reassembles():
    txn = gen_transfer_pool(1, seed=b"reasm")[0]
    r = TpuReasm()
    # deliver in 3 fragments on one stream, interleaved with another stream
    a, b, c = txn[:100], txn[100:180], txn[180:]
    assert r.append(("c1", 5), a) is None
    assert r.append(("c2", 1), b"other-stream") is None
    assert r.append(("c1", 5), b) is None
    out = r.append(("c1", 5), c, fin=True)
    assert out == txn
    assert ft.txn_parse(out) is not None
    assert r.metrics["published"] == 1
    assert r.active() == 1  # c2 still open


def test_single_fragment_fast_path():
    r = TpuReasm()
    assert r.append(("c", 0), b"whole", fin=True) == b"whole"
    assert r.active() == 0


def test_oversize_stream_cancelled():
    r = TpuReasm(mtu=100)
    assert r.append(("c", 0), b"x" * 80) is None
    assert r.append(("c", 0), b"x" * 40, fin=True) is None  # 120 > 100
    assert r.metrics["oversz"] == 1
    assert r.active() == 0


def test_oversize_poison_is_sticky():
    """A long stream crossing the MTU mid-flight must not re-open fresh
    slots with every continuation frame (it would churn-evict honest
    streams) nor publish its tail as a txn at FIN."""
    r = TpuReasm(depth=2, mtu=100)
    r.append(("honest", 1), b"partial")
    assert r.append(("big", 0), b"x" * 120) is None  # poisoned at once
    # continuation frames are swallowed: no eviction churn, no new slots
    for _ in range(10):
        assert r.append(("big", 0), b"y" * 50) is None
    assert r.metrics["evicted"] == 0
    # the FIN tail is NOT published as a bogus whole txn
    assert r.append(("big", 0), b"tail", fin=True) is None
    # the honest stream survived and the key is reusable afterwards
    assert r.append(("honest", 1), b"!", fin=True) == b"partial!"
    assert r.append(("big", 0), b"fresh", fin=True) == b"fresh"


def test_slot_stealing_under_pressure():
    r = TpuReasm(depth=4)
    for i in range(4):
        r.append(("stalled", i), b"frag")
    r.append(("stalled", 1), b"more")  # refresh stream 1's recency
    r.append(("new", 99), b"data")     # pool full: steals stream 0
    assert r.metrics["evicted"] == 1
    assert r.active() == 4
    # the stolen stream is gone; finishing it starts a FRESH slot
    out = r.append(("stalled", 0), b"tail", fin=True)
    assert out == b"tail"
    # the refreshed stream survived the steal
    assert r.append(("stalled", 1), b"!", fin=True) == b"fragmore!"


def test_cancel():
    r = TpuReasm()
    r.append(("c", 0), b"partial")
    assert r.cancel(("c", 0))
    assert not r.cancel(("c", 0))
    assert r.active() == 0
