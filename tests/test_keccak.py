"""Keccak-256 tests: public known-answer vectors (legacy 0x01 padding) +
host-vs-device differential across lengths straddling the rate boundary."""

import numpy as np
import pytest

from firedancer_tpu.ops import keccak256 as kk

# public known-answer vectors for legacy keccak256 (Ethereum flavor)
KAT = {
    b"": "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470",
    b"abc": "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45",
    b"The quick brown fox jumps over the lazy dog":
        "4d741b6f1eb29cb2a9b9911c82f56fa8d73b04959d3d9d222895df6c0b28aa15",
}


def test_host_known_answers():
    for msg, hexdigest in KAT.items():
        assert kk.keccak256_host(msg).hex() == hexdigest


def test_host_rate_boundaries():
    # 135/136/137 bytes straddle the single-block padding edge
    for n in (135, 136, 137, 271, 272, 273):
        out = kk.keccak256_host(b"\xaa" * n)
        assert len(out) == 32
        assert out != kk.keccak256_host(b"\xaa" * (n + 1))


def test_device_matches_host():
    rng = np.random.default_rng(9)
    msgs = [
        b"",
        b"abc",
        rng.bytes(64),
        rng.bytes(135),
        rng.bytes(136),
        rng.bytes(137),
        rng.bytes(200),
    ]
    max_len = 256
    b = len(msgs)
    arr = np.zeros((max_len, b), dtype=np.int32)
    lens = np.zeros((b,), dtype=np.int32)
    for i, m in enumerate(msgs):
        arr[: len(m), i] = np.frombuffer(m, dtype=np.uint8)
        lens[i] = len(m)
    out = np.asarray(kk.keccak256_msg(arr, lens, max_len))
    for i, m in enumerate(msgs):
        assert out[:, i].astype(np.uint8).tobytes() == kk.keccak256_host(m), i
