"""Native txn parser differential tests: valid corpus, every rejection
case the python parser's tests exercise, mutation fuzz, and a throughput
sanity race."""

import time

import numpy as np
import pytest

from firedancer_tpu.protocol import txn as ft
from tests.test_txn import keypair, simple_legacy

try:
    from firedancer_tpu.protocol import txn_native as fn

    fn._load()
    HAVE_NATIVE = True
except Exception:
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(not HAVE_NATIVE, reason="no g++ toolchain")


def both(payload: bytes):
    return ft.txn_parse(payload), fn.txn_parse_native(payload)


def assert_agree(payload: bytes):
    py, nat = both(payload)
    assert (py is None) == (nat is None), payload.hex()
    if py is not None:
        assert py == nat


def _v0_with_luts():
    import hashlib

    secret, pub = keypair(b"v0nat")
    msg = ft.message_build(
        version=ft.V0,
        signature_cnt=1,
        readonly_signed_cnt=0,
        readonly_unsigned_cnt=1,
        acct_addrs=[pub, ft.SYSTEM_PROGRAM],
        recent_blockhash=bytes(32),
        instrs=[ft.InstrSpec(program_id=1, accounts=bytes([0, 2]), data=b"zz")],
        luts=[
            ft.LutSpec(
                table_addr=hashlib.sha256(b"t%d" % i).digest(),
                writable=bytes([1]),
                readonly=bytes([7, 9]),
            )
            for i in range(2)
        ],
    )
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    return ft.txn_assemble([ref.sign(secret, msg)], msg)


def test_valid_corpus_agrees():
    from firedancer_tpu.runtime.benchg import gen_transfer_pool

    corpus = (
        [simple_legacy(n_extra_accts=k, n_instr=j, data=b"d" * (k + 1))
         for k in (1, 3) for j in (1, 4)]
        + gen_transfer_pool(8, seed=b"natcorp")
        + [_v0_with_luts()]
        + [ft.vote_txn(keypair(b"nv")[0], b"V" * 32, 7, bytes(32))]
    )
    for p in corpus:
        py, nat = both(p)
        assert py is not None and py == nat
        # packed bytes themselves match txn_pack exactly
        assert fn.txn_parse_packed(p) == ft.txn_pack(py)


def test_rejections_agree():
    base = simple_legacy()
    bad_cases = [
        b"",
        b"\x00",
        base[:-1],                      # truncated tail
        base + b"\x00",                 # trailing byte
        b"\x00" + base[1:],             # sig_cnt 0
        base[:200],                     # truncated mid-message
        bytes([200]) + base[1:],        # sig_cnt > 127
    ]
    # header count mismatch
    b2 = bytearray(base)
    b2[65] = 9
    bad_cases.append(bytes(b2))
    # versioned with version 1
    b3 = bytearray(base)
    b3[65] = 0x81
    bad_cases.append(bytes(b3))
    for p in bad_cases:
        py, nat = both(p)
        assert py is None and nat is None, p.hex()


def test_mutation_fuzz_agrees():
    rng = np.random.default_rng(0xF12E)
    seeds = [simple_legacy(), _v0_with_luts()]
    for seed in seeds:
        for _ in range(400):
            m = bytearray(seed)
            for _ in range(rng.integers(1, 4)):
                op = rng.integers(0, 3)
                if op == 0 and len(m) > 1:
                    m[rng.integers(0, len(m))] = rng.integers(0, 256)
                elif op == 1 and len(m) > 2:
                    del m[rng.integers(0, len(m))]
                else:
                    m.insert(rng.integers(0, len(m) + 1), rng.integers(0, 256))
            assert_agree(bytes(m))
    # pure noise
    for n in (0, 1, 50, 300, 1232, 1233):
        for _ in range(30):
            assert_agree(rng.bytes(n))


def test_native_parse_speed():
    p = simple_legacy(n_extra_accts=3, n_instr=3)
    n = 3000
    t0 = time.perf_counter()
    for _ in range(n):
        fn.txn_parse_packed(p)
    nat_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        ft.txn_parse(p)
    py_dt = time.perf_counter() - t0
    print(f"native parse {n/nat_dt:,.0f}/s vs python {n/py_dt:,.0f}/s")
    assert nat_dt < py_dt
