"""Consensus backtester: deterministic decision traces through the real
ghost/tower over partition scenarios."""

import json

from firedancer_tpu.choreo import backtest as bt


def test_partition_scenario_votes_majority_and_heals():
    events, total = bt.synth_partition_scenario()
    res = bt.run_scenario(events, total_stake=total)
    assert res.blocks > 20 and res.cluster_votes > 100
    # every vote landed on chain A (even slots): the majority fork
    voted = [d.slot for d in res.decisions if d.action == "vote"]
    assert voted and all(s % 2 == 0 for s in voted)
    # votes are monotonically increasing (tower can never re-vote back)
    assert voted == sorted(voted)
    # after healing the tower keeps deepening on the converged chain
    assert res.decisions[-1].action == "vote"
    assert res.summary()["final_head"] == max(voted)


def test_determinism():
    events, total = bt.synth_partition_scenario()
    a = bt.run_scenario(events, total_stake=total)
    b = bt.run_scenario(events, total_stake=total)
    assert [(d.step, d.action, d.slot) for d in a.decisions] == \
        [(d.step, d.action, d.slot) for d in b.decisions]


def test_lockout_abstain_on_fork_flip():
    """A head flip to a non-descendant fork while locked out must
    abstain with the lockout reason."""
    v = "aa" * 32
    w = "bb" * 32
    events = [
        {"t": "block", "slot": 1, "parent": 0},
        {"t": "block", "slot": 2, "parent": 1},
        {"t": "vote", "voter": v, "slot": 2, "stake": 60},
        {"t": "tick"},                      # vote 2
        {"t": "block", "slot": 3, "parent": 1},  # competing fork
        {"t": "vote", "voter": w, "slot": 3, "stake": 100},
        {"t": "tick"},                      # head flips to 3: locked out
    ]
    res = bt.run_scenario(events)
    assert [d.action for d in res.decisions] == ["vote", "abstain"]
    assert "lockout" in res.decisions[1].reason


def test_scenario_file_roundtrip(tmp_path):
    events, total = bt.synth_partition_scenario(slots=6)
    p = tmp_path / "s.json"
    p.write_text(json.dumps({"total_stake": total, "events": events}))
    loaded, meta = bt.load_scenario(str(p))
    assert loaded == events and meta["total_stake"] == total


def test_backtest_cli(capsys):
    from firedancer_tpu.__main__ import main

    assert main(["backtest"]) == 0
    out = capsys.readouterr().out
    assert "vote" in out and '"final_head"' in out
