"""BN254 (alt_bn128): G1 group law, subgroup/curve validation, the ate
pairing (bilinearity + degeneracy), EIP-196/197 wire encodings, and the
VM syscall bridge."""

import pytest

from firedancer_tpu.ops import bn254 as bn


def test_g1_group_law():
    g = bn.G1_GEN
    d = bn.g1_add(g, g)
    # independent affine doubling check
    s = 3 * pow(4, bn.P - 2, bn.P) % bn.P
    x3 = (s * s - 2) % bn.P
    y3 = (s * (1 - x3) - 2) % bn.P
    assert d == (x3, y3)
    assert bn.g1_mul(g, 2) == d
    assert bn.g1_add(d, (g[0], bn.P - g[1])) == g  # 2G - G = G
    assert bn.g1_mul(g, 3) == bn.g1_add(d, g)
    # identity
    assert bn.g1_add(g, None) == g
    assert bn.g1_add(None, None) is None
    assert bn.g1_mul(g, 0) is None
    assert bn.g1_mul(g, bn.R) is None  # order-r subgroup


def test_g1_rejects_off_curve():
    with pytest.raises(bn.Bn254Error, match="not on G1"):
        bn.g1_check((1, 3))
    with pytest.raises(bn.Bn254Error, match="out of range"):
        bn.g1_check((bn.P, 2))


def test_g2_validation():
    q = bn.g2_embed(bn.G2_GEN)
    assert q is not None
    bad = ((1, 2), (3, 4))
    with pytest.raises(bn.Bn254Error, match="not on twisted G2"):
        bn.g2_embed(bad)


def test_pairing_inverse_pair_cancels():
    neg_g1 = (1, bn.P - 2)
    assert bn.pairing_check([(bn.G1_GEN, bn.G2_GEN), (neg_g1, bn.G2_GEN)])
    assert not bn.pairing_check([(bn.G1_GEN, bn.G2_GEN)])
    assert bn.pairing_check([])  # empty product is 1


def test_pairing_bilinearity():
    """e(aG, Q) * e(-G, aQ) == 1 — scalar moves across the pairing."""
    a = 7
    ag = bn.g1_mul(bn.G1_GEN, a)
    neg_g = (1, bn.P - 2)
    q = bn.g2_embed(bn.G2_GEN)
    aq = bn._ec_mul(q, a)
    p_ag = (bn.f12_from_fp(ag[0]), bn.f12_from_fp(ag[1]))
    p_ng = (bn.f12_from_fp(neg_g[0]), bn.f12_from_fp(neg_g[1]))
    acc = bn.f12_mul(bn.miller_loop(q, p_ag), bn.miller_loop(aq, p_ng))
    assert bn.f12_pow(acc, bn._FINAL_EXP) == bn.f12_one()


def test_wire_encodings():
    g = bn.G1_GEN
    enc = bn.g1_encode(g)
    assert bn.g1_decode(enc) == g
    assert bn.g1_decode(bytes(64)) is None
    assert bn.g1_encode(None) == bytes(64)
    # add via wire: G + G == 2G
    out = bn.alt_bn128_addition(enc + enc)
    assert bn.g1_decode(out) == bn.g1_add(g, g)
    # mul via wire
    out = bn.alt_bn128_multiplication(enc + (5).to_bytes(32, "big"))
    assert bn.g1_decode(out) == bn.g1_mul(g, 5)
    # pairing via wire: e(G,Q)·e(-G,Q) == 1
    g2e = (
        bn.G2_GEN[0][0].to_bytes(32, "big")
        + bn.G2_GEN[0][1].to_bytes(32, "big")
        + bn.G2_GEN[1][0].to_bytes(32, "big")
        + bn.G2_GEN[1][1].to_bytes(32, "big")
    )
    neg = bn.g1_encode((1, bn.P - 2))
    res = bn.alt_bn128_pairing(enc + g2e + neg + g2e)
    assert res == (1).to_bytes(32, "big")
    with pytest.raises(bn.Bn254Error, match="multiple of 192"):
        bn.alt_bn128_pairing(b"\x00" * 100)


def test_vm_syscall_bridge():
    from firedancer_tpu.flamenco import vm as fvm
    from tests.test_executor import lddw
    from tests.test_sbpf import ins

    g = bn.g1_encode(bn.G1_GEN)
    # input = G || G via the input region; result written back to input+128
    text = (
        ins(0xB7, dst=1, imm=fvm.ALT_BN128_ADD)
        + lddw(2, fvm.MM_INPUT)
        + ins(0xB7, dst=3, imm=128)
        + lddw(4, fvm.MM_INPUT + 128)
        + ins(0x85, imm=fvm.SYSCALL_SOL_ALT_BN128)
        + ins(0x95)
    )
    from tests.test_vm import run_text

    m = run_text(text, input_data=g + g + bytes(64))
    fvm.register_default_syscalls(m)
    assert m.run() == 0
    out = bytes(m.regions[3].data[128:192])
    assert bn.g1_decode(out) == bn.g1_add(bn.G1_GEN, bn.G1_GEN)
