"""Per-pubkey comb-cache verify path vs the generic kernel and host ref."""

import hashlib

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # XLA-compile-heavy tier (see conftest)

import jax.numpy as jnp

from firedancer_tpu.ops import sigverify as sv
from firedancer_tpu.ops.ref import ed25519_ref as ref

MAXLEN = 64


def _batch(n_signers, n_elems, corrupt=()):
    keys = []
    for i in range(n_signers):
        secret = hashlib.sha256(b"compat%d" % i).digest()
        keys.append((secret, ref.public_key(secret)))
    msg_a = np.zeros((MAXLEN, n_elems), np.uint8)
    ln = np.zeros((n_elems,), np.int32)
    sig_a = np.zeros((64, n_elems), np.uint8)
    pk_a = np.zeros((32, n_elems), np.uint8)
    signer = np.zeros((n_elems,), np.int32)
    for i in range(n_elems):
        s_idx = i % n_signers
        secret, pub = keys[s_idx]
        m = b"txn %d payload" % i
        sig = bytearray(ref.sign(secret, m))
        if i in corrupt:
            sig[7] ^= 0x40
        msg_a[: len(m), i] = np.frombuffer(m, np.uint8)
        ln[i] = len(m)
        sig_a[:, i] = np.frombuffer(bytes(sig), np.uint8)
        pk_a[:, i] = np.frombuffer(pub, np.uint8)
        signer[i] = s_idx
    return keys, msg_a, ln, sig_a, pk_a, signer


def test_comb_fill_and_cached_verify_match_generic():
    n_signers, n_elems = 3, 12
    corrupt = {5, 9}
    keys, msg_a, ln, sig_a, pk_a, signer = _batch(
        n_signers, n_elems, corrupt
    )

    # fill the bank with each signer's comb
    pk_fill = np.stack(
        [np.frombuffer(pub, np.uint8) for _, pub in keys], axis=1
    )
    tables, ok = sv.comb_fill(jnp.asarray(pk_fill))
    assert np.asarray(ok).all(), "honest pubkeys must fill"
    bank = sv.bank_alloc(n_signers + 2)
    bank = sv.bank_install(bank, tables, jnp.asarray(np.arange(n_signers)))

    got = np.asarray(
        sv.ed25519_verify_batch_cached(
            jnp.asarray(msg_a), jnp.asarray(ln), jnp.asarray(sig_a),
            jnp.asarray(pk_a), bank, jnp.asarray(signer),
            max_msg_len=MAXLEN,
        )
    )
    want = np.asarray(
        sv.ed25519_verify_batch(
            jnp.asarray(msg_a), jnp.asarray(ln), jnp.asarray(sig_a),
            jnp.asarray(pk_a), max_msg_len=MAXLEN,
        )
    )
    expect = np.ones(n_elems, bool)
    for i in corrupt:
        expect[i] = False
    assert (want == expect).all(), "generic kernel baseline wrong"
    assert (got == expect).all(), "cached kernel disagrees"


def test_comb_fill_rejects_bad_pubkeys():
    # a non-point pubkey and a small-order pubkey must come back not-ok
    bad = np.zeros((32, 2), np.uint8)
    bad[:, 0] = np.frombuffer(hashlib.sha256(b"junk").digest(), np.uint8)
    # identity point encoding (y=1): small order
    ident = bytearray(32)
    ident[0] = 1
    bad[:, 1] = np.frombuffer(bytes(ident), np.uint8)
    _tables, ok = sv.comb_fill(jnp.asarray(bad))
    ok = np.asarray(ok)
    # index 0 may or may not decode as a curve point (hash bytes), but the
    # identity at index 1 is definitely small-order
    assert not ok[1]


def test_bank_reinstall_overwrites_slot():
    keys, msg_a, ln, sig_a, pk_a, signer = _batch(2, 4)
    pk_fill = np.stack(
        [np.frombuffer(pub, np.uint8) for _, pub in keys], axis=1
    )
    tables, ok = sv.comb_fill(jnp.asarray(pk_fill))
    bank = sv.bank_alloc(2)
    # install signer1's comb into BOTH slots, then fix slot 0
    bank = sv.bank_install(
        bank, tables[..., 1:2].repeat(2, axis=-1), jnp.asarray([0, 1])
    )
    bank = sv.bank_install(bank, tables[..., 0:1], jnp.asarray([0]))
    got = np.asarray(
        sv.ed25519_verify_batch_cached(
            jnp.asarray(msg_a), jnp.asarray(ln), jnp.asarray(sig_a),
            jnp.asarray(pk_a), bank, jnp.asarray(signer),
            max_msg_len=MAXLEN,
        )
    )
    assert got.all()


def test_verify_stage_comb_path_end_to_end():
    """Stage-level: repeated signers promote into the device comb bank and
    the cached lane produces the same accept/reject decisions (the
    integration bench.py exercises on TPU; here on the CPU mesh)."""
    import os as _os
    import time as _time

    from firedancer_tpu.runtime.verify import VerifyStage, decode_verified
    from firedancer_tpu.tango import shm

    uid = f"{_os.getpid()}_{int(_time.monotonic_ns() % 1_000_000)}"
    nv = shm.ShmLink.create(f"fdtpu_cnv_{uid}", depth=256, mtu=1232)
    vo = shm.ShmLink.create(f"fdtpu_cvo_{uid}", depth=256, mtu=4096)
    try:
        from firedancer_tpu.runtime.benchg import gen_transfer_pool

        stage = VerifyStage(
            "verify0",
            ins=[shm.Consumer(nv, lazy=8)],
            outs=[shm.Producer(vo)],
            batch=8,
            max_msg_len=256,
            batch_deadline_s=0.0005,
            comb_slots=4,
            promote_threshold=2,
        )
        sink = shm.Consumer(vo, lazy=8)
        prod = shm.Producer(nv)
        pool = gen_transfer_pool(24, seed=b"combstage", n_payers=2)
        corrupt_idx = 21
        bad = bytearray(pool[corrupt_idx])
        bad[5] ^= 0x20  # inside signature 0
        pool[corrupt_idx] = bytes(bad)

        got = []

        def pump(n_iters=400):
            for _ in range(n_iters):
                stage.run_once()
                res = sink.poll()
                if isinstance(res, tuple):
                    got.append(res[1])

        # wave 1: both payers seen >= threshold on the generic lane
        for p in pool[:8]:
            assert prod.try_publish(p)
        pump()
        stage.during_housekeeping()  # builds + installs the combs
        pump()
        assert stage.metrics.get("comb_filled") == 2

        # wave 2: every txn's signer is banked -> cached lane
        for p in pool[8:]:
            assert prod.try_publish(p)
        pump()
        stage.flush()
        pump(100)
        assert stage.metrics.get("comb_elems") > 0, "cached lane unused"
        assert stage.metrics.get("verify_fail") == 1
        payloads = {decode_verified(f)[0] for f in got}
        want = {p for i, p in enumerate(pool) if i != corrupt_idx}
        assert payloads == want
    finally:
        for l in (nv, vo):
            l.close()
            l.unlink()
