"""Conformance-fixture harness: committed corpus must be 100% green, and
the wire codec must round-trip (the adapter is only as good as its
protobuf layer)."""

import os

from firedancer_tpu.flamenco import solcompat as sc

CORPUS = os.path.join(os.path.dirname(__file__), "fixtures", "instr")


def test_corpus_green():
    res = sc.run_corpus(CORPUS)
    assert len(res) >= 20, "committed corpus missing"
    bad = {p: d.mismatches for p, d in res.items() if not d.ok}
    assert not bad, bad


def test_fixture_wire_roundtrip():
    paths = []
    for dirpath, _d, files in os.walk(CORPUS):
        paths += [os.path.join(dirpath, f) for f in files if f.endswith(".fix")]
    assert paths
    for p in paths:
        with open(p, "rb") as f:
            raw = f.read()
        fix = sc.InstrFixture.decode(raw)
        again = sc.InstrFixture.decode(fix.encode())
        assert again.input.program_id == fix.input.program_id
        assert len(again.input.accounts) == len(fix.input.accounts)
        for x, y in zip(again.input.accounts, fix.input.accounts):
            assert (x.address, x.lamports, x.data, x.owner) == (
                y.address, y.lamports, y.data, y.owner
            )
        assert again.output.result == fix.output.result
        assert len(again.output.modified_accounts) == len(
            fix.output.modified_accounts
        )


def test_effects_detect_wrong_lamports():
    """The comparer must actually catch a wrong post-state (harness
    self-check: a fixture demanding the wrong balance fails)."""
    p = os.path.join(CORPUS, "system", "transfer_ok.fix")
    fix = sc.load_fixture(p)
    fix.output.modified_accounts[0].lamports += 1
    d = sc.run_instr_fixture(fix)
    assert not d.ok and any("lamports" in m for m in d.mismatches)


def test_effects_detect_unexpected_modification():
    """An account changed but absent from modified_accounts fails."""
    p = os.path.join(CORPUS, "system", "transfer_ok.fix")
    fix = sc.load_fixture(p)
    fix.output.modified_accounts = fix.output.modified_accounts[:1]
    d = sc.run_instr_fixture(fix)
    assert not d.ok


def test_features_decode_packed_and_unpacked():
    """proto3 packs repeated fixed64 (protoc/nanopb corpora); our encoder
    emits unpacked WT_I64 — the decoder must accept both."""
    feats = [0x1122334455667788, 0x99AABBCCDDEEFF00]
    packed = b"".join(f.to_bytes(8, "little") for f in feats)
    # EpochContext{ FeatureSet{ features } } at InstrContext field 9
    inner = sc.enc_field(1, sc.WT_LEN, packed)
    buf = sc.enc_field(9, sc.WT_LEN, sc.enc_field(1, sc.WT_LEN, inner))
    assert sc.InstrContext.decode(buf).features == feats
    c = sc.InstrContext(features=feats)
    assert sc.InstrContext.decode(c.encode()).features == feats
