"""Turbine tree tests: deterministic per-shred shuffles, leader root
computation, two-level fanout children, whole-tree coverage invariants."""

import hashlib

import pytest

from firedancer_tpu.protocol import shred as fs
from firedancer_tpu.protocol import wsample as ws
from firedancer_tpu.protocol.shred_dest import NO_DEST, Dest, ShredDest, shred_seed


def _mk_cluster(n_staked=12, n_unstaked=4):
    dests = [
        Dest(pubkey=hashlib.sha256(b"v%d" % i).digest(),
             stake=(n_staked - i) * 1_000_000)
        for i in range(n_staked)
    ] + [
        Dest(pubkey=hashlib.sha256(b"u%d" % i).digest(), stake=0)
        for i in range(n_unstaked)
    ]
    stakes = [(d.pubkey, d.stake) for d in dests if d.stake > 0]
    lsched = ws.epoch_leaders(epoch=1, slot0=0, slot_cnt=1000, stakes=stakes)
    return dests, lsched


def _mk_shreds(slot, idxs):
    return [
        bytes(
            fs.build_data_shred(
                slot=slot, idx=i, version=1, fec_set_idx=0, parent_off=1,
                flags=0, payload=b"x", merkle_proof_cnt=6,
            )
        )
        for i in idxs
    ]


def test_seed_is_shred_specific():
    leader = b"L" * 32
    s1 = shred_seed(5, 0, True, leader)
    assert s1 != shred_seed(5, 1, True, leader)   # idx matters
    assert s1 != shred_seed(6, 0, True, leader)   # slot matters
    assert s1 != shred_seed(5, 0, False, leader)  # data/code matters
    assert s1 == shred_seed(5, 0, True, leader)   # deterministic


def test_compute_first_excludes_leader_self():
    dests, lsched = _mk_cluster()
    slot = 8
    leader = lsched.leader_for_slot(slot)
    sd = ShredDest(dests, lsched, source=leader)
    shreds = _mk_shreds(slot, range(20))
    roots = sd.compute_first(shreds)
    assert len(roots) == 20
    leader_idx = [i for i, d in enumerate(dests) if d.pubkey == leader][0]
    for r in roots:
        assert r != NO_DEST
        assert r != leader_idx  # never send to self
    # deterministic, and different shreds get different roots sometimes
    assert roots == sd.compute_first(shreds)
    assert len(set(roots)) > 1


def test_every_validator_agrees_on_the_tree():
    """The root's children lists and each child's own view compose into a
    consistent tree: whoever the leader sends to (root) forwards to level
    1; level-1 nodes forward to level 2; nobody is contacted twice."""
    dests, lsched = _mk_cluster(n_staked=10, n_unstaked=3)
    slot = 4
    leader = lsched.leader_for_slot(slot)
    fanout = 3
    shreds = _mk_shreds(slot, [7])
    sd_leader = ShredDest(dests, lsched, source=leader)
    root_idx = sd_leader.compute_first(shreds)[0]
    seen = {root_idx}
    frontier = [root_idx]
    leader_idx = [i for i, d in enumerate(dests) if d.pubkey == leader][0]
    while frontier:
        nxt = []
        for v in frontier:
            sd_v = ShredDest(dests, lsched, source=dests[v].pubkey)
            for child in sd_v.compute_children(shreds, fanout=fanout)[0]:
                assert child not in seen, "validator contacted twice"
                assert child != leader_idx
                seen.add(child)
                nxt.append(child)
        frontier = nxt
    # full coverage: every non-leader validator got the shred
    assert seen == set(range(len(dests))) - {leader_idx}


def test_children_layout_two_level():
    dests, lsched = _mk_cluster(n_staked=30, n_unstaked=0)
    slot = 12
    leader = lsched.leader_for_slot(slot)
    shreds = _mk_shreds(slot, [0])
    fanout = 4
    # find the shuffled root (position 0): it must have exactly fanout kids
    sd_leader = ShredDest(dests, lsched, source=leader)
    root = sd_leader.compute_first(shreds)[0]
    kids = ShredDest(dests, lsched, source=dests[root].pubkey).compute_children(
        shreds, fanout=fanout
    )[0]
    assert len(kids) == fanout
    # a level-1 node has up to fanout children; level-2 nodes have none
    lvl2 = ShredDest(dests, lsched, source=dests[kids[0]].pubkey).compute_children(
        shreds, fanout=fanout
    )[0]
    assert len(lvl2) <= fanout
    for g in lvl2:
        assert (
            ShredDest(dests, lsched, source=dests[g].pubkey).compute_children(
                shreds, fanout=fanout
            )[0]
            == []
        )


def test_leader_gets_empty_children():
    dests, lsched = _mk_cluster()
    slot = 0
    leader = lsched.leader_for_slot(slot)
    sd = ShredDest(dests, lsched, source=leader)
    assert sd.compute_children(_mk_shreds(slot, [0]), fanout=3) == [[]]


def test_unstaked_only_cluster():
    dests = [Dest(pubkey=hashlib.sha256(b"q%d" % i).digest(), stake=0)
             for i in range(5)]
    # leader from a separate staked set (not in dests contact list is not
    # allowed; put leader in as unstaked too)
    stakes = [(dests[0].pubkey, 1)]
    lsched = ws.epoch_leaders(epoch=2, slot0=0, slot_cnt=100, stakes=stakes)
    sd = ShredDest(dests, lsched, source=dests[0].pubkey)
    roots = sd.compute_first(_mk_shreds(0, [1, 2, 3]))
    for r in roots:
        assert r != NO_DEST and r != 0  # picked an unstaked non-self dest


def test_field_keyed_queries_match_buf_apis():
    """first_for/children_for (the receipt-ledger audit's entry points:
    tree queries from recorded (slot, idx, type) triples, no wire bytes)
    must agree exactly with the buf-parsing APIs."""
    dests, lsched = _mk_cluster()
    slot = 3
    idxs = [0, 1, 5, 9]
    shreds = _mk_shreds(slot, idxs)
    leader = lsched.leader_for_slot(slot)
    sd_leader = ShredDest(dests, lsched, source=leader)
    assert sd_leader.compute_first(shreds) == [
        sd_leader.first_for(slot, i, True) for i in idxs
    ]
    src = next(d.pubkey for d in dests if d.pubkey != leader)
    sd = ShredDest(dests, lsched, source=src)
    assert sd.compute_children(shreds, fanout=3) == [
        sd.children_for(slot, i, True, fanout=3) for i in idxs
    ]
    # the data/code distinction feeds the seed: same idx, different tree
    assert any(
        sd.children_for(slot, i, True, fanout=3)
        != sd.children_for(slot, i, False, fanout=3)
        for i in idxs
    )
