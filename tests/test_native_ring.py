"""Native (C++) ring tests: build via ctypes, differential interop with
the Python rings in BOTH directions, overrun semantics, a throughput
sanity race (native must beat the Python loop), and the full-protocol
differential suite — randomized op scripts replayed against every lane
combination (native/Python producer x native/Python consumer) under
credit exhaustion, dcache wrap, forced overrun + resync, and the lazy
fseq cadence, asserting identical metas, payloads, publish outcomes,
ovrn_cnt, and fseq values; plus stage-level pipeline diffs with the
FDTPU_NATIVE_RING toggle flipped and a mixed-lane topology."""

import os
import time

import numpy as np
import pytest

from firedancer_tpu.tango import shm
from firedancer_tpu.tango.rings import MCache
from firedancer_tpu.utils.rng import Rng

try:
    from firedancer_tpu.tango import native as fn

    fn._load()
    HAVE_NATIVE = True
except Exception:  # toolchain-less environment
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(not HAVE_NATIVE, reason="no g++ toolchain")


@pytest.fixture
def link():
    l = shm.ShmLink.create(
        f"fdtpu_nr_{os.getpid()}_{int(time.monotonic_ns() % 1_000_000)}",
        depth=64,
        mtu=256,
    )
    yield l
    l.close()
    l.unlink()


def test_native_producer_python_consumer(link):
    prod = fn.NativeProducer(link)
    cons = shm.Consumer(link)
    msgs = [b"frag-%03d" % i for i in range(50)]
    for i, m in enumerate(msgs):
        prod.publish(m, sig=1000 + i)
    got = []
    while len(got) < 50:
        res = cons.poll()
        assert res != shm.POLL_OVERRUN
        if isinstance(res, tuple):
            got.append(res)
    assert [p for _, p in got] == msgs
    assert [int(m[MCache.COL_SIG]) for m, _ in got] == list(range(1000, 1050))
    assert all(int(m[MCache.COL_TSPUB]) > 0 for m, _ in got)


def test_python_producer_native_consumer(link):
    prod = shm.Producer(link)
    cons = fn.NativeConsumer(link)
    for i in range(40):
        assert prod.try_publish(b"x%d" % i, sig=i)
    got = []
    while len(got) < 40:
        res = cons.poll()
        if isinstance(res, tuple):
            got.append(res)
    assert [p for _, p in got] == [b"x%d" % i for i in range(40)]
    assert cons.ovrn_cnt == 0


def test_native_overrun_detection(link):
    prod = fn.NativeProducer(link)
    cons = fn.NativeConsumer(link)
    # lap the consumer: 64-deep ring, publish 100 without consuming
    for i in range(100):
        prod.publish(b"y%d" % i, sig=i)
    res = cons.poll()
    assert res == shm.POLL_OVERRUN
    assert cons.ovrn_cnt >= 100 - 64
    # after resync the stream continues coherently
    res = cons.poll()
    assert isinstance(res, tuple)


def test_native_bulk_roundtrip_and_speed(link):
    n = 20_000
    payload = b"z" * 200
    prod = fn.NativeProducer(link)
    cons = fn.NativeConsumer(link)
    # interleave in bulk chunks sized under the ring depth so nothing drops
    t0 = time.perf_counter()
    done = 0
    while done < n:
        burst = min(48, n - done)
        prod.publish_n(payload, burst)
        got = cons.consume_n(burst)
        assert got == burst
        done += burst
    native_dt = time.perf_counter() - t0

    prod2 = shm.Producer(link)
    prod2.seq = prod.seq
    cons2 = shm.Consumer(link, lazy=16)
    cons2.seq = prod.seq
    cons2.publish_progress()  # native path never touched the fseq: prime
    # the credit loop so the python producer isn't still at lap 0
    t0 = time.perf_counter()
    done = 0
    while done < n:
        burst = min(48, n - done)
        for _ in range(burst):
            assert prod2.try_publish(payload)
        got = 0
        while got < burst:
            if isinstance(cons2.poll(), tuple):
                got += 1
        done += burst
    py_dt = time.perf_counter() - t0
    rate = n / native_dt
    print(f"native ring: {rate:,.0f} frags/s vs python {n / py_dt:,.0f}")
    assert native_dt < py_dt, "native hot path should outrun the Python loop"


# -- full-protocol differential suite -----------------------------------------
#
# The same deterministic op script replays against every producer x
# consumer lane combination on its own fresh link; everything observable
# at the protocol level must match across lanes: publish outcomes (credit
# exhaustion points), consumed metas (all columns except tspub, which is
# a wall-clock stamp) and payloads, overrun events + ovrn_cnt, and the
# fseq progress values the lazy cadence publishes.

DIFF_DEPTH = 16
DIFF_MTU = 192


def _mk_link(tag):
    return shm.ShmLink.create(
        f"fdtpu_nrd_{tag}_{os.getpid()}_{int(time.monotonic_ns() % 1_000_000)}",
        depth=DIFF_DEPTH,
        mtu=DIFF_MTU,
    )


def _endpoints(link, prod_native, cons_native, *, reliable, lazy):
    prod = (fn.NativeProducer(link, reliable_fseq_idx=reliable)
            if prod_native else shm.Producer(link, reliable))
    cons = (fn.NativeConsumer(link, lazy=lazy)
            if cons_native else shm.Consumer(link, lazy=lazy))
    return prod, cons


def _script(seed, n_steps=240):
    """Deterministic op list: bursts of publishes (sizes spanning 0 to
    full-mtu so the compact dcache allocator wraps), consume runs, and
    stalls that push the producer into credit exhaustion."""
    r = Rng(seed)
    ops = []
    for _ in range(n_steps):
        k = r.roll(3)
        if k == 0:
            ops.append(("pub", 1 + r.roll(8),
                        [r.roll(DIFF_MTU + 1) for _ in range(8)]))
        elif k == 1:
            ops.append(("consume", 1 + r.roll(10)))
        else:
            ops.append(("stall",))
    ops.append(("consume", 4 * DIFF_DEPTH))  # final drain
    return ops


def _run_script(link, prod, cons, ops):
    """Replay `ops`; returns the observable event log."""
    log = []
    pub_i = 0
    for op in ops:
        if op[0] == "pub":
            for j in range(op[1]):
                sz = op[2][j % len(op[2])]
                payload = bytes((pub_i + i) & 0xFF for i in range(sz))
                ok = prod.try_publish(payload, sig=(pub_i << 56) | 7,
                                      tsorig=1_000_000 + pub_i)
                log.append(("pub", pub_i, bool(ok)))
                if ok:
                    pub_i += 1
        elif op[0] == "consume":
            for _ in range(op[1]):
                res = cons.poll()
                if res == shm.POLL_EMPTY:
                    log.append(("empty",))
                    break
                if res == shm.POLL_OVERRUN:
                    log.append(("ovrn", cons.ovrn_cnt))
                    continue
                meta, payload = res
                # all meta columns except tspub (wall-clock stamp)
                log.append(("frag", tuple(int(meta[c]) for c in range(6)),
                            payload))
        log.append(("fseq", link.fseqs[0].query()))
    log.append(("final", cons.ovrn_cnt, prod.seq, cons.seq))
    return log


LANES = [(False, False), (True, True), (True, False), (False, True)]


def test_lane_protocol_parity_credit_gated():
    """Reliable consumer: credit exhaustion + dcache wrap + lazy fseq
    cadence identical across all four lane combos."""
    ops = _script(0xC0FFEE)
    logs = []
    for pn, cn in LANES:
        link = _mk_link(f"cg{int(pn)}{int(cn)}")
        try:
            prod, cons = _endpoints(link, pn, cn, reliable=None, lazy=5)
            logs.append(_run_script(link, prod, cons, ops))
        finally:
            link.close()
            link.unlink()
    for i in range(1, len(logs)):
        assert logs[i] == logs[0], f"lane {LANES[i]} diverged from python"


def test_lane_protocol_parity_lazy_zero():
    """lazy=0 publishes progress after EVERY frag on both lanes
    (shm.Consumer's `since_publish >= lazy`), so a credit-gated producer
    never wedges on one lane only."""
    ops = _script(0xBEEF, n_steps=120)
    logs = []
    for pn, cn in LANES:
        link = _mk_link(f"lz{int(pn)}{int(cn)}")
        try:
            prod, cons = _endpoints(link, pn, cn, reliable=None, lazy=0)
            logs.append(_run_script(link, prod, cons, ops))
        finally:
            link.close()
            link.unlink()
    for i in range(1, len(logs)):
        assert logs[i] == logs[0], f"lane {LANES[i]} diverged from python"


def test_lane_protocol_parity_overrun_resync():
    """Free-running producer (no reliable fseqs): forced overruns, the
    resync point, and ovrn_cnt accounting identical across lanes."""
    ops = _script(0xFEED, n_steps=160)
    logs = []
    for pn, cn in LANES:
        link = _mk_link(f"ov{int(pn)}{int(cn)}")
        try:
            prod, cons = _endpoints(link, pn, cn, reliable=[], lazy=7)
            logs.append(_run_script(link, prod, cons, ops))
        finally:
            link.close()
            link.unlink()
    assert any(e[0] == "ovrn" for e in logs[0]), "script must force overruns"
    for i in range(1, len(logs)):
        assert logs[i] == logs[0], f"lane {LANES[i]} diverged from python"


def test_publish_burst_matches_per_frag_lane(link):
    """fdr_publish_burst: one crossing, same wire frames + credit gate as
    per-frag try_publish on the Python lane."""
    prod = fn.NativeProducer(link)
    cons = shm.Consumer(link, lazy=16)
    items = [(b"burst-%03d" % i, (i << 48) | 5, 10_000 + i)
             for i in range(100)]
    n = prod.publish_burst(items)
    assert n == link.depth  # credit-gated: one ring of frames, no more
    got = []
    while True:
        res = cons.poll()
        if res == shm.POLL_EMPTY:
            break
        assert isinstance(res, tuple)
        got.append(res)
    assert [p for _, p in got] == [it[0] for it in items[:n]]
    assert [int(m[MCache.COL_SIG]) for m, _ in got] == \
        [it[1] for it in items[:n]]
    assert [int(m[MCache.COL_TSORIG]) for m, _ in got] == \
        [it[2] for it in items[:n]]
    cons.publish_progress()
    prod.refresh_credits()
    # credits released: the tail goes through on the next burst
    assert prod.publish_burst(items[n:]) == len(items) - n


def test_drainer_round_robin_union(link):
    """BurstDrainer: one crossing drains multiple links round-robin; the
    meta table carries mcache-compatible columns + in_idx, and payloads
    land at the table's arena offsets."""
    link2 = _mk_link("dr2")
    try:
        p1 = fn.NativeProducer(link)
        p2 = fn.NativeProducer(link2)
        c1 = fn.NativeConsumer(link, lazy=64)
        c2 = fn.NativeConsumer(link2, lazy=64)
        for i in range(6):
            assert p1.try_publish(b"a%d" % i, sig=100 + i, tsorig=1 + i)
        for i in range(3):
            assert p2.try_publish(b"b%d" % i, sig=200 + i, tsorig=50 + i)
        dr = fn.BurstDrainer([c1, c2], max_frags=16)
        n, rr, d_ovr = dr.drain(0, 16)
        assert n == 9 and d_ovr == 0
        rows = [dr.meta[i] for i in range(n)]
        payloads = [
            dr.arena[int(r[2]): int(r[2]) + int(r[3])].tobytes()
            for r in rows
        ]
        # round-robin interleave while both have frags, then the rest
        assert payloads == [b"a0", b"b0", b"a1", b"b1", b"a2", b"b2",
                            b"a3", b"a4", b"a5"]
        assert [int(r[7]) for r in rows] == [0, 1, 0, 1, 0, 1, 0, 0, 0]
        assert [int(r[1]) for r in rows[:2]] == [100, 200]
        assert [int(r[5]) for r in rows[:2]] == [1, 50]
        # nothing left
        n2, _, _ = dr.drain(rr, 16)
        assert n2 == 0
    finally:
        link2.close()
        link2.unlink()


def test_native_teardown_no_buffer_error(link):
    """Satellite: live native endpoints registered with the ShmLink are
    detached by close(), so the mapping closes on the clean path (no
    BufferError fallback) even while the endpoint objects are alive."""
    prod = fn.NativeProducer(link)
    cons = fn.NativeConsumer(link)
    assert prod.try_publish(b"x", sig=1)
    assert isinstance(cons.poll(), tuple)
    # instrument the underlying close: the BufferError fallback must not
    # run (pre-fix, a pinned from_buffer view forced it on every run)
    raised = []
    real_close = link._shm.close

    def checked_close():
        try:
            real_close()
        except BufferError:
            raised.append(True)
            raise

    link._shm.close = checked_close
    link.close()  # endpoints still referenced by this frame
    assert prod._keep is None and cons._keep is None  # detached
    assert not raised, "close took the BufferError fallback path"
    # a detached endpoint refuses instead of passing NULL into C
    with pytest.raises(RuntimeError):
        cons.poll()
    with pytest.raises(RuntimeError):
        prod.try_publish(b"y")


def test_native_fseq_idx_range_checked(link):
    """shm lane parity: an out-of-range fseq index raises at
    construction instead of silently addressing past the fseq region
    (the adjacent cnc words)."""
    with pytest.raises(IndexError):
        fn.NativeConsumer(link, fseq_idx=link.n_fseq)
    with pytest.raises(IndexError):
        fn.NativeProducer(link, reliable_fseq_idx=[link.n_fseq])


def test_env_toggle_restores_python_rings(link, monkeypatch):
    monkeypatch.setenv("FDTPU_NATIVE_RING", "0")
    assert not shm.native_ring_enabled()
    assert type(shm.make_producer(link)) is shm.Producer
    assert type(shm.make_consumer(link)) is shm.Consumer
    monkeypatch.delenv("FDTPU_NATIVE_RING")
    assert shm.native_ring_enabled()
    assert type(shm.make_producer(link)) is fn.NativeProducer
    assert type(shm.make_consumer(link)) is fn.NativeConsumer


# -- stage-level pipeline diffs ----------------------------------------------


def _run_small_pipeline(n_txns=96):
    from firedancer_tpu.models.leader import build_leader_pipeline

    pipe = build_leader_pipeline(
        n_verify=1, n_bank=2, pool_size=n_txns, gen_limit=n_txns,
        batch=32, max_msg_len=256, verify_precomputed=True,
    )
    try:
        pipe.run(until_txns=n_txns, max_iters=400_000)
        return {
            "executed": sum(b.metrics.get("txn_exec") for b in pipe.banks),
            "pack_in": pipe.pack.metrics.get("txn_in"),
            "verified": pipe.verifies[0].metrics.get("txn_verified"),
            "mixins": pipe.poh.metrics.get("mixins"),
            "store_sets": pipe.store.metrics.get("sets_stored"),
            "overruns": sum(s.metrics.get("overrun") for s in pipe.stages),
            "store_lat_count":
                pipe.store.metrics.hist("frag_latency_ns")["count"],
        }
    finally:
        pipe.close()


def test_pipeline_stream_diff_env_toggle(monkeypatch):
    """The same pipeline run with the native ring plane ON and OFF moves
    the identical stream: every conservation count matches, nothing is
    lost to overruns on either lane, and the latency histograms populate
    under the native lane (tsorig rides the C++ rings unchanged)."""
    monkeypatch.setenv("FDTPU_NATIVE_RING", "0")
    off = _run_small_pipeline()
    monkeypatch.setenv("FDTPU_NATIVE_RING", "1")
    on = _run_small_pipeline()
    assert off["overruns"] == 0 and on["overruns"] == 0
    assert on["executed"] == off["executed"] == 96
    for key in ("pack_in", "verified", "mixins", "store_sets"):
        assert on[key] == off[key], key
    assert on["store_lat_count"] > 0


def test_pipeline_mixed_lane_topology(monkeypatch):
    """Wire-format compatibility IN SITU: alternate lanes per endpoint
    while building the pipeline (native producer feeding a Python
    consumer and vice versa on the same links) — the stream still moves
    end to end."""
    flip = {"n": 0}
    real_mp, real_mc = shm.make_producer, shm.make_consumer

    def mixed_producer(link, reliable_fseq_idx=None):
        flip["n"] += 1
        if flip["n"] % 2:
            return shm.Producer(link, reliable_fseq_idx)
        return real_mp(link, reliable_fseq_idx)

    def mixed_consumer(link, fseq_idx=0, lazy=64):
        flip["n"] += 1
        if flip["n"] % 2:
            return real_mc(link, fseq_idx=fseq_idx, lazy=lazy)
        return shm.Consumer(link, fseq_idx=fseq_idx, lazy=lazy)

    monkeypatch.setattr(shm, "make_producer", mixed_producer)
    monkeypatch.setattr(shm, "make_consumer", mixed_consumer)
    out = _run_small_pipeline()
    assert out["executed"] == 96
    assert out["overruns"] == 0


def test_lossy_consumer_wraps_native(link):
    """Chaos satellite: the seeded drop/dup/reorder shim runs over a
    native consumer — including sig values >= 2^63 surviving the meta
    copy — and keeps the no-stranded-frag liveness contract."""
    from firedancer_tpu.tango.lossy import LossyConsumer

    prod = fn.NativeProducer(link)
    inner = fn.NativeConsumer(link, lazy=16)
    lossy = LossyConsumer(inner, Rng(0xD00D), drop_p=0.25, dup_p=0.2,
                          reorder_p=0.2)
    sigs = [(1 << 63) | i for i in range(40)]
    for i, s in enumerate(sigs):
        assert prod.try_publish(b"L%02d" % i, sig=s, tsorig=5 + i)
    got = []
    while True:
        assert lossy.has_pending() or not lossy._ready
        res = lossy.poll()
        if res == shm.POLL_EMPTY:
            break
        assert res != shm.POLL_OVERRUN
        meta, payload = res
        got.append((int(meta[MCache.COL_SIG]), payload))
    delivered = len(got) - lossy.duplicated
    assert delivered == 40 - lossy.dropped
    assert lossy.dropped > 0 and lossy.duplicated > 0
    assert all(s >= (1 << 63) for s, _ in got)  # u64 sigs intact


# -- ring reattach (in-place restart, ISSUE 14) -------------------------------
#
# A supervisor respawn reattaches a stage's endpoints to the LIVE shm
# segment: the consumer resumes at its published fseq, the producer at
# the frontier recovered from its own mcache (seq + dcache chunk + the
# published-sig dedup window).  Both lanes must recover identically —
# these tests kill endpoints mid-burst and assert no frag is lost,
# duplicated or reordered, and that flow-control credits conserve.


def _reattach_roundtrip(make_prod, make_cons, link):
    """Drive a kill/reattach cycle at BOTH ends of one link."""
    prod = make_prod(link)
    cons = make_cons(link)
    got = []

    def drain(c, n=10**9):
        k = 0
        while k < n:
            r = c.poll()
            if not isinstance(r, tuple):
                break
            got.append((int(r[0][MCache.COL_SIG]), bytes(r[1])))
            k += 1

    for i in range(40):
        assert prod.try_publish(b"A%03d" % i, sig=i)
    drain(cons, 17)  # mid-burst...
    cons.publish_progress()
    replay_from = 17
    drain(cons, 6)  # ...consume past the published fseq, then die
    assert len(got) == 23
    # the consumer's replacement resumes at the PUBLISHED progress: the
    # 6 unacknowledged frags replay (at-least-once at ring level; the
    # stage-level publish guard is what dedups a relay's output)
    cons2 = make_cons(link)
    assert cons2.resume() == replay_from
    del got[replay_from:]
    drain(cons2)
    assert [s for s, _ in got] == list(range(40))
    assert [p for _, p in got] == [b"A%03d" % i for i in range(40)]
    # now the producer dies: its replacement recovers frontier + chunk
    # + the published-sig window from the ring alone
    prod2 = make_prod(link)
    sigs = prod2.resume()
    assert prod2.seq == 40
    assert sigs == set(range(40))
    cons2.publish_progress()
    prod2.refresh_credits()
    depth = link.depth
    # credits conserve: everything consumed+acked -> full budget again
    assert prod2.cr_avail == depth
    for i in range(40, 40 + depth):
        assert prod2.try_publish(b"B%03d" % i, sig=i)
    assert prod2.cr_avail == 0  # exactly depth spent, none leaked
    drain(cons2)
    assert [s for s, _ in got] == list(range(40 + depth))
    # payload bytes intact across the chunk-cursor recovery: nothing
    # overwrote an in-flight frag
    assert got[-1][1] == b"B%03d" % (40 + depth - 1)


def test_ring_reattach_native_lane(link):
    _reattach_roundtrip(fn.NativeProducer,
                        lambda l: fn.NativeConsumer(l, lazy=8), link)


def test_ring_reattach_python_twin(link):
    _reattach_roundtrip(shm.Producer,
                        lambda l: shm.Consumer(l, lazy=8), link)


def test_ring_reattach_mixed_lanes(link):
    """The respawned endpoint need not be the same lane as its
    predecessor (a restarted child without a toolchain joins with
    Python rings): a native producer's ring recovers under a Python
    successor and vice versa."""
    prod = fn.NativeProducer(link)
    for i in range(10):
        assert prod.try_publish(b"M%02d" % i, sig=100 + i)
    py = shm.Producer(link)
    sigs = py.resume()
    assert py.seq == 10 and sigs == set(range(100, 110))
    assert py.try_publish(b"M10", sig=110)
    nat = fn.NativeProducer(link)
    assert nat.resume() == set(range(100, 111))
    assert nat.seq == 11
    cons = shm.Consumer(link, lazy=4)
    seen = []
    while True:
        r = cons.poll()
        if not isinstance(r, tuple):
            break
        seen.append(int(r[0][MCache.COL_SIG]))
    assert seen == list(range(100, 111))
