"""Native (C++) ring tests: build via ctypes, differential interop with
the Python rings in BOTH directions, overrun semantics, and a throughput
sanity race (native must beat the Python loop)."""

import os
import time

import pytest

from firedancer_tpu.tango import shm
from firedancer_tpu.tango.rings import MCache

try:
    from firedancer_tpu.tango import native as fn

    fn._load()
    HAVE_NATIVE = True
except Exception:  # toolchain-less environment
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(not HAVE_NATIVE, reason="no g++ toolchain")


@pytest.fixture
def link():
    l = shm.ShmLink.create(
        f"fdtpu_nr_{os.getpid()}_{int(time.monotonic_ns() % 1_000_000)}",
        depth=64,
        mtu=256,
    )
    yield l
    l.close()
    l.unlink()


def test_native_producer_python_consumer(link):
    prod = fn.NativeProducer(link)
    cons = shm.Consumer(link)
    msgs = [b"frag-%03d" % i for i in range(50)]
    for i, m in enumerate(msgs):
        prod.publish(m, sig=1000 + i)
    got = []
    while len(got) < 50:
        res = cons.poll()
        assert res != shm.POLL_OVERRUN
        if isinstance(res, tuple):
            got.append(res)
    assert [p for _, p in got] == msgs
    assert [int(m[MCache.COL_SIG]) for m, _ in got] == list(range(1000, 1050))
    assert all(int(m[MCache.COL_TSPUB]) > 0 for m, _ in got)


def test_python_producer_native_consumer(link):
    prod = shm.Producer(link)
    cons = fn.NativeConsumer(link)
    for i in range(40):
        assert prod.try_publish(b"x%d" % i, sig=i)
    got = []
    while len(got) < 40:
        res = cons.poll()
        if isinstance(res, tuple):
            got.append(res)
    assert [p for _, p in got] == [b"x%d" % i for i in range(40)]
    assert cons.ovrn_cnt == 0


def test_native_overrun_detection(link):
    prod = fn.NativeProducer(link)
    cons = fn.NativeConsumer(link)
    # lap the consumer: 64-deep ring, publish 100 without consuming
    for i in range(100):
        prod.publish(b"y%d" % i, sig=i)
    res = cons.poll()
    assert res == shm.POLL_OVERRUN
    assert cons.ovrn_cnt >= 100 - 64
    # after resync the stream continues coherently
    res = cons.poll()
    assert isinstance(res, tuple)


def test_native_bulk_roundtrip_and_speed(link):
    n = 20_000
    payload = b"z" * 200
    prod = fn.NativeProducer(link)
    cons = fn.NativeConsumer(link)
    # interleave in bulk chunks sized under the ring depth so nothing drops
    t0 = time.perf_counter()
    done = 0
    while done < n:
        burst = min(48, n - done)
        prod.publish_n(payload, burst)
        got = cons.consume_n(burst)
        assert got == burst
        done += burst
    native_dt = time.perf_counter() - t0

    prod2 = shm.Producer(link)
    prod2.seq = prod.seq
    cons2 = shm.Consumer(link, lazy=16)
    cons2.seq = prod.seq
    cons2.publish_progress()  # native path never touched the fseq: prime
    # the credit loop so the python producer isn't still at lap 0
    t0 = time.perf_counter()
    done = 0
    while done < n:
        burst = min(48, n - done)
        for _ in range(burst):
            assert prod2.try_publish(payload)
        got = 0
        while got < burst:
            if isinstance(cons2.poll(), tuple):
                got += 1
        done += burst
    py_dt = time.perf_counter() - t0
    rate = n / native_dt
    print(f"native ring: {rate:,.0f} frags/s vs python {n / py_dt:,.0f}")
    assert native_dt < py_dt, "native hot path should outrun the Python loop"
