"""pcap container + UDP encap/decap + shredcap record/replay, including
the pipeline replay harness (captured txns -> ingest sink) the reference
exercises with its pcap tooling."""

import hashlib
import struct

import pytest

from firedancer_tpu.utils import pcap


def test_pcap_roundtrip(tmp_path):
    p = str(tmp_path / "c.pcap")
    frames = [b"frame-%d" % i * (i + 1) for i in range(5)]
    with pcap.PcapWriter(p) as w:
        for i, fr in enumerate(frames):
            w.write_pkt(fr, ts=100.5 + i)
    got = list(pcap.iter_pcap(p))
    assert [g[1] for g in got] == frames
    assert abs(got[0][0] - 100.5) < 1e-5


def test_pcap_rejects_bad_magic(tmp_path):
    p = str(tmp_path / "bad.pcap")
    open(p, "wb").write(b"\x00" * 24)
    with pytest.raises(pcap.PcapError):
        list(pcap.iter_pcap(p))


def test_pcap_tolerates_truncated_tail(tmp_path):
    p = str(tmp_path / "t.pcap")
    with pcap.PcapWriter(p) as w:
        w.write_pkt(b"whole", ts=1.0)
    blob = open(p, "rb").read()
    open(p, "wb").write(blob + struct.pack("<IIII", 2, 0, 100, 100) + b"xx")
    got = list(pcap.iter_pcap(p))
    assert len(got) == 1 and got[0][1] == b"whole"


def test_udp_encap_decap():
    f = pcap.encap_udp(b"hello", src=("10.0.0.1", 53), dst=("10.0.0.2", 8001))
    out = pcap.decap_udp(f)
    assert out is not None
    payload, src, dst = out
    assert payload == b"hello"
    assert src == ("10.0.0.1", 53)
    assert dst == ("10.0.0.2", 8001)
    # non-UDP frame is skipped, not an error
    assert pcap.decap_udp(b"\x00" * 60) is None


def test_replay_udp_port_filter(tmp_path):
    p = str(tmp_path / "mix.pcap")
    with pcap.PcapWriter(p) as w:
        w.write_udp(b"gossip", dst=("127.0.0.1", 7000))
        w.write_udp(b"tpu-1", dst=("127.0.0.1", 9000))
        w.write_udp(b"repair", dst=("127.0.0.1", 7001))
        w.write_udp(b"tpu-2", dst=("127.0.0.1", 9000))
    got = []
    n = pcap.replay_udp(p, lambda pl, src: got.append(pl), port=9000)
    assert n == 2 and got == [b"tpu-1", b"tpu-2"]


def test_replay_capture_through_txn_ingest(tmp_path):
    """The harness position: capture signed txns as UDP, replay into a
    parse+verify sink without any live network."""
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.protocol import txn as ft
    from firedancer_tpu.runtime.benchg import gen_transfer_pool

    p = str(tmp_path / "tpu.pcap")
    pool = gen_transfer_pool(12, seed=b"pcap")
    with pcap.PcapWriter(p) as w:
        for t in pool:
            w.write_udp(t, dst=("127.0.0.1", 9001))

    accepted = []

    def ingest(payload, _src):
        d = ft.txn_parse(payload)
        assert d is not None
        sig = d.signatures(payload)[0]
        pk = list(d.signers(payload))[0]
        assert ref.verify(d.message(payload), sig, pk)
        accepted.append(payload)

    n = pcap.replay_udp(p, ingest, port=9001)
    assert n == 12 and accepted == pool


# -- shredcap -----------------------------------------------------------------


def test_shredcap_record_replay_into_resolver(tmp_path):
    import numpy as np

    from firedancer_tpu.flamenco import shredcap
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.runtime import shredder as fsh
    from firedancer_tpu.runtime.fec_resolver import (
        FecResolver, entry_batch_from_sets,
    )

    secret = hashlib.sha256(b"shredcap").digest()
    pub = ref.public_key(secret)
    sh = fsh.Shredder(signer=lambda r: ref.sign(secret, r))
    rng = np.random.default_rng(3)
    batch = bytes(rng.integers(0, 256, 8000, dtype=np.uint8))
    (st,) = sh.entry_batch_to_fec_sets(batch, slot=9)

    cap = str(tmp_path / "shreds.pcap")
    with shredcap.ShredCapWriter(cap) as w:
        # record a lossy stream: drop one data shred, keep parity
        for b in st.data_shreds[1:]:
            w.write(b)
        for b in st.parity_shreds:
            w.write(b)
    assert w.count == len(st.data_shreds) - 1 + len(st.parity_shreds)

    res = FecResolver(verify_sig=lambda r, s: ref.verify(r, s, pub))
    done = shredcap.replay_into_resolver(cap, res)
    assert len(done) == 1
    assert entry_batch_from_sets(done) == batch
