"""murmur3_32 + siphash13 against their public vector sets (the Solana
syscall-id table and the SipHash-1-3 reference vectors)."""

import pytest

from firedancer_tpu.flamenco import vm as fvm
from firedancer_tpu.ops.smallhash import murmur3_32, siphash13, syscall_id

# the Solana syscall-id derivation (public protocol constants)
SYSCALL_IDS = {
    "abort": 0xB6FC1A11,
    "sol_panic_": 0x686093BB,
    "sol_log_": 0x207559BD,
    "sol_sha256": 0x11F49D86,
    "sol_keccak256": 0xD7793ABB,
    "sol_secp256k1_recover": 0x17E40350,
    "sol_blake3": 0x174C5122,
}


def test_murmur3_syscall_ids():
    for name, want in SYSCALL_IDS.items():
        assert syscall_id(name) == want, name


def test_vm_ids_are_name_hashes():
    """flamenco/vm's registered ids ARE the murmur3 name hashes."""
    assert fvm.SYSCALL_SOL_SHA256 == syscall_id("sol_sha256")
    assert fvm.SYSCALL_SOL_KECCAK256 == syscall_id("sol_keccak256")
    assert fvm.SYSCALL_SOL_LOG == syscall_id("sol_log_")
    assert fvm.SYSCALL_SOL_SECP256K1_RECOVER == syscall_id("sol_secp256k1_recover")


def test_murmur3_seed_and_tails():
    # seed changes the hash; all tail lengths exercise the partial block
    assert murmur3_32(b"abcd", 1) != murmur3_32(b"abcd", 2)
    vals = {murmur3_32(b"x" * n) for n in range(9)}
    assert len(vals) == 9


def test_siphash13_reference_vectors():
    """The SipHash-1-3 vector set: key 00..0f, message 00,01,..,i-1
    (the same public vectors the reference embeds, test_siphash13.c)."""
    key = bytes(range(16))
    expect = [
        0xABAC0158050FC4DC,
        0xC9F49BF37D57CA93,
        0x82CB9B024DC7D44D,
        0x8BF80AB8E7DDF7FB,
        0xCF75576088D38328,
    ]
    for i, want in enumerate(expect):
        msg = bytes(range(i))
        assert siphash13(key, msg) == want, i


def test_siphash13_keyed():
    assert siphash13(bytes(16), b"data") != siphash13(bytes(range(16)), b"data")
    with pytest.raises(ValueError):
        siphash13(b"short", b"")
