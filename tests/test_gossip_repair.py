"""Gossip + repair tests over real loopback sockets: signed contact-info
exchange (push, pull, CRDS upsert rules) and shred repair round trips
feeding the FEC resolver."""

import hashlib
import time

import numpy as np
import pytest

from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.runtime import gossip as fg
from firedancer_tpu.runtime import repair as fr
from firedancer_tpu.runtime import shredder as fsh
from firedancer_tpu.runtime.fec_resolver import FecResolver


def _secret(tag):
    return hashlib.sha256(tag).digest()


def _drain(nodes, rounds=20):
    for _ in range(rounds):
        for n in nodes:
            n.poll()
        time.sleep(0.005)


# -- gossip -------------------------------------------------------------------


def test_gossip_push_and_pull():
    a = fg.GossipNode(_secret(b"ga"), tvu_port=1001, repair_port=1002)
    b = fg.GossipNode(_secret(b"gb"), tvu_port=2001)
    c = fg.GossipNode(_secret(b"gc"))
    try:
        # a pushes to b: b learns a
        a.push([b.addr])
        _drain([a, b])
        assert len(b.table) == 1
        info = b.table[a.pubkey]
        assert (info.tvu_port, info.repair_port) == (1001, 1002)
        assert info.gossip_port == a.addr[1]
        # c pulls from b: learns b AND (transitively) a's original record
        c.pull(b.addr)
        _drain([a, b, c])
        assert set(c.table) == {a.pubkey, b.pubkey}
    finally:
        for n in (a, b, c):
            n.close()


def test_gossip_newest_wallclock_wins():
    clock = [1000]
    a = fg.GossipNode(_secret(b"wa"), clock=lambda: clock[0])
    b = fg.GossipNode(_secret(b"wb"))
    try:
        a.push([b.addr])
        _drain([a, b])
        assert b.table[a.pubkey].wallclock == 1000
        # stale replay (same record again) does not upsert
        a.push([b.addr])
        _drain([a, b])
        assert b.metrics["rec_stale"] >= 1
        # fresher record wins
        clock[0] = 2000
        a.push([b.addr])
        _drain([a, b])
        assert b.table[a.pubkey].wallclock == 2000
    finally:
        a.close()
        b.close()


def test_gossip_rejects_bad_signature():
    a = fg.GossipNode(_secret(b"sa"))
    b = fg.GossipNode(_secret(b"sb"))
    try:
        rec = bytearray(a._self_record())
        rec[40] ^= 1  # corrupt the body after signing
        b.sock.sendto(a._push_frame([bytes(rec)]), b.addr)
        # direct local delivery: b polls its own socket
        _drain([b])
        assert b.metrics["rec_rejected"] == 1
        assert not b.table
    finally:
        a.close()
        b.close()


# -- repair -------------------------------------------------------------------


@pytest.fixture
def stored_set():
    secret = _secret(b"leader-r")
    pub = ref.public_key(secret)
    sh = fsh.Shredder(signer=lambda root: ref.sign(secret, root))
    batch = bytes(np.random.default_rng(3).integers(0, 256, 4000, dtype=np.uint8))
    (st,) = sh.entry_batch_to_fec_sets(batch, slot=44)
    store = fr.Blockstore()
    store.put_set(st)
    return st, store, pub


def test_repair_round_trip(stored_set):
    st, store, pub = stored_set
    server = fr.RepairServer(store)
    client = fr.RepairClient(_secret(b"requester"))
    try:
        got = client.request(
            server.addr, 44, 2, spin=server.poll, max_spins=2000
        )
        assert got == st.data_shreds[2]
        assert server.served == 1
        # missing shred: no response
        assert client.request(server.addr, 44, 999, spin=server.poll,
                              max_spins=500) is None
    finally:
        server.close()
        client.close()


def test_repair_refuses_unsigned(stored_set):
    _, store, _ = stored_set
    server = fr.RepairServer(store)
    try:
        import socket as s

        from firedancer_tpu.flamenco import repair_wire as rw

        sock = s.socket(s.AF_INET, s.SOCK_DGRAM)
        # valid-shaped but garbage-signed request
        header = rw.RepairRequestHeader(
            signature=b"\x00" * 64, sender=b"\x00" * 32,
            recipient=b"\x00" * 32, timestamp=0, nonce=1,
        )
        req = rw.PROTOCOL.encode(
            ("window_index", rw.WindowIndex(header, 44, 2))
        )
        sock.sendto(req, server.addr)
        for _ in range(50):
            server.poll()
        assert server.refused == 1 and server.served == 0
        sock.close()
    finally:
        server.close()


def test_repair_completes_fec_set(stored_set):
    """The repair consumer: a resolver missing shreds repairs them and
    completes the set — merkle checks still gate the repaired bytes."""
    st, store, pub = stored_set
    server = fr.RepairServer(store)
    client = fr.RepairClient(_secret(b"requester2"))
    try:
        res = FecResolver(verify_sig=lambda r, s: ref.verify(r, s, pub))
        # deliver only the parity shreds (turbine "lost" all data)
        done = None
        for buf in st.parity_shreds[: len(st.data_shreds) - 1]:
            done = res.add_shred(buf) or done
        assert done is None
        # repair exactly one data shred to cross the threshold
        got = client.request(server.addr, 44, 0, spin=server.poll,
                             max_spins=2000)
        done = res.add_shred(got)
        assert done is not None
        assert [bytes(b) for b in done.data_shreds] == list(st.data_shreds)
    finally:
        server.close()
        client.close()


def test_repair_wire_signing_rule():
    """ServeRepair signature covers tag + post-signature bytes; any
    tamper of slot/index/nonce breaks it."""
    import hashlib

    from firedancer_tpu.flamenco import repair_wire as rw

    secret = hashlib.sha256(b"rw").digest()
    header = rw.RepairRequestHeader(
        signature=bytes(64), sender=ref.public_key(secret),
        recipient=b"R" * 32, timestamp=123, nonce=7,
    )
    enc = rw.sign_request(secret, "window_index", rw.WindowIndex(header, 9, 3))
    out = rw.verify_request(enc)
    assert out is not None
    name, payload = out
    assert name == "window_index"
    assert (payload.slot, payload.shred_index, payload.header.nonce) == (9, 3, 7)
    bad = bytearray(enc)
    bad[-1] ^= 1  # tamper the shred_index tail
    assert rw.verify_request(bytes(bad)) is None
    # responses: shred || nonce
    r = rw.encode_response(b"shredbytes", 7)
    assert rw.decode_response(r) == (b"shredbytes", 7)


def test_repair_highest_and_orphan(stored_set):
    st, store, pub = stored_set
    server = fr.RepairServer(store)
    client = fr.RepairClient(_secret(b"hw-req"))
    try:
        hi = client.request(server.addr, 44, 0, spin=server.poll,
                            max_spins=2000, kind="highest_window_index")
        assert hi is not None
        import firedancer_tpu.protocol.shred as fsh2

        s = fsh2.parse(hi)
        assert s.slot == 44
        assert s.idx == max(i for (sl, i) in store._shreds if sl == 44)
        orph = client.request(server.addr, 44, 0, spin=server.poll,
                              max_spins=2000, kind="orphan")
        assert orph is not None and fsh2.parse(orph).slot == 44
    finally:
        server.close()
        client.close()
