"""Gossip + repair tests over real loopback sockets: signed contact-info
exchange (push, pull, CRDS upsert rules) and shred repair round trips
feeding the FEC resolver."""

import hashlib
import time

import numpy as np
import pytest

from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.runtime import gossip as fg
from firedancer_tpu.runtime import repair as fr
from firedancer_tpu.runtime import shredder as fsh
from firedancer_tpu.runtime.fec_resolver import FecResolver


def _secret(tag):
    return hashlib.sha256(tag).digest()


def _drain(nodes, rounds=20):
    for _ in range(rounds):
        for n in nodes:
            n.poll()
        time.sleep(0.005)


# -- gossip -------------------------------------------------------------------


def test_gossip_push_and_pull():
    a = fg.GossipNode(_secret(b"ga"), tvu_port=1001, repair_port=1002)
    b = fg.GossipNode(_secret(b"gb"), tvu_port=2001)
    c = fg.GossipNode(_secret(b"gc"))
    try:
        # a pushes to b: b learns a
        a.push([b.addr])
        _drain([a, b])
        assert len(b.table) == 1
        info = b.table[a.pubkey]
        assert (info.tvu_port, info.repair_port) == (1001, 1002)
        assert info.gossip_port == a.addr[1]
        # c pulls from b: learns b AND (transitively) a's original record
        c.pull(b.addr)
        _drain([a, b, c])
        assert set(c.table) == {a.pubkey, b.pubkey}
    finally:
        for n in (a, b, c):
            n.close()


def test_gossip_newest_wallclock_wins():
    clock = [1000]
    a = fg.GossipNode(_secret(b"wa"), clock=lambda: clock[0])
    b = fg.GossipNode(_secret(b"wb"))
    try:
        a.push([b.addr])
        _drain([a, b])
        assert b.table[a.pubkey].wallclock == 1000
        # stale replay (same record again) does not upsert
        a.push([b.addr])
        _drain([a, b])
        assert b.metrics["rec_stale"] >= 1
        # fresher record wins
        clock[0] = 2000
        a.push([b.addr])
        _drain([a, b])
        assert b.table[a.pubkey].wallclock == 2000
    finally:
        a.close()
        b.close()


def test_gossip_rejects_bad_signature():
    a = fg.GossipNode(_secret(b"sa"))
    b = fg.GossipNode(_secret(b"sb"))
    try:
        rec = bytearray(a._self_record())
        rec[40] ^= 1  # corrupt the body after signing
        b.sock.sendto(a._push_frame([bytes(rec)]), b.addr)
        # direct local delivery: b polls its own socket
        _drain([b])
        assert b.metrics["rec_rejected"] == 1
        assert not b.table
    finally:
        a.close()
        b.close()


# -- repair -------------------------------------------------------------------


@pytest.fixture
def stored_set():
    secret = _secret(b"leader-r")
    pub = ref.public_key(secret)
    sh = fsh.Shredder(signer=lambda root: ref.sign(secret, root))
    batch = bytes(np.random.default_rng(3).integers(0, 256, 4000, dtype=np.uint8))
    (st,) = sh.entry_batch_to_fec_sets(batch, slot=44)
    store = fr.Blockstore()
    store.put_set(st)
    return st, store, pub


def test_repair_round_trip(stored_set):
    st, store, pub = stored_set
    server = fr.RepairServer(store)
    client = fr.RepairClient(_secret(b"requester"))
    try:
        got = client.request(
            server.addr, 44, 2, spin=server.poll, max_spins=2000
        )
        assert got == st.data_shreds[2]
        assert server.served == 1
        # missing shred: no response
        assert client.request(server.addr, 44, 999, spin=server.poll,
                              max_spins=500) is None
    finally:
        server.close()
        client.close()


def test_repair_refuses_unsigned(stored_set):
    _, store, _ = stored_set
    server = fr.RepairServer(store)
    try:
        import socket as s

        from firedancer_tpu.flamenco import repair_wire as rw

        sock = s.socket(s.AF_INET, s.SOCK_DGRAM)
        # valid-shaped but garbage-signed request
        header = rw.RepairRequestHeader(
            signature=b"\x00" * 64, sender=b"\x00" * 32,
            recipient=b"\x00" * 32, timestamp=0, nonce=1,
        )
        req = rw.PROTOCOL.encode(
            ("window_index", rw.WindowIndex(header, 44, 2))
        )
        sock.sendto(req, server.addr)
        for _ in range(50):
            server.poll()
        assert server.refused == 1 and server.served == 0
        sock.close()
    finally:
        server.close()


def test_repair_completes_fec_set(stored_set):
    """The repair consumer: a resolver missing shreds repairs them and
    completes the set — merkle checks still gate the repaired bytes."""
    st, store, pub = stored_set
    server = fr.RepairServer(store)
    client = fr.RepairClient(_secret(b"requester2"))
    try:
        res = FecResolver(verify_sig=lambda r, s: ref.verify(r, s, pub))
        # deliver only the parity shreds (turbine "lost" all data)
        done = None
        for buf in st.parity_shreds[: len(st.data_shreds) - 1]:
            done = res.add_shred(buf) or done
        assert done is None
        # repair exactly one data shred to cross the threshold
        got = client.request(server.addr, 44, 0, spin=server.poll,
                             max_spins=2000)
        done = res.add_shred(got)
        assert done is not None
        assert [bytes(b) for b in done.data_shreds] == list(st.data_shreds)
    finally:
        server.close()
        client.close()


def test_repair_wire_signing_rule():
    """ServeRepair signature covers tag + post-signature bytes; any
    tamper of slot/index/nonce breaks it."""
    import hashlib

    from firedancer_tpu.flamenco import repair_wire as rw

    secret = hashlib.sha256(b"rw").digest()
    header = rw.RepairRequestHeader(
        signature=bytes(64), sender=ref.public_key(secret),
        recipient=b"R" * 32, timestamp=123, nonce=7,
    )
    enc = rw.sign_request(secret, "window_index", rw.WindowIndex(header, 9, 3))
    out = rw.verify_request(enc)
    assert out is not None
    name, payload = out
    assert name == "window_index"
    assert (payload.slot, payload.shred_index, payload.header.nonce) == (9, 3, 7)
    bad = bytearray(enc)
    bad[-1] ^= 1  # tamper the shred_index tail
    assert rw.verify_request(bytes(bad)) is None
    # responses: shred || nonce
    r = rw.encode_response(b"shredbytes", 7)
    assert rw.decode_response(r) == (b"shredbytes", 7)


def test_repair_highest_and_orphan(stored_set):
    st, store, pub = stored_set
    server = fr.RepairServer(store)
    client = fr.RepairClient(_secret(b"hw-req"))
    try:
        hi = client.request(server.addr, 44, 0, spin=server.poll,
                            max_spins=2000, kind="highest_window_index")
        assert hi is not None
        import firedancer_tpu.protocol.shred as fsh2

        s = fsh2.parse(hi)
        assert s.slot == 44
        assert s.idx == max(i for (sl, i) in store._shreds if sl == 44)
        orph = client.request(server.addr, 44, 0, spin=server.poll,
                              max_spins=2000, kind="orphan")
        assert orph is not None and fsh2.parse(orph).slot == 44
    finally:
        server.close()
        client.close()


# -- round-4 gossip machinery: bloom pulls, prune, stake-weighted push --------


def _mk_node(name, **kw):
    import hashlib

    from firedancer_tpu.runtime.gossip import GossipNode

    return GossipNode(hashlib.sha256(b"gn:" + name).digest(), **kw)


def _settle(nodes, rounds=20):
    import time as _t

    for _ in range(rounds):
        for n in nodes:
            n.poll()
        _t.sleep(0.002)


def test_bloom_pull_sends_only_misses():
    """B holds records A already has plus new ones; A's filtered pull
    must transfer the new ones while B skips what A holds."""
    a, b = _mk_node(b"A"), _mk_node(b"B")
    seeds = [_mk_node(b"peer%d" % i) for i in range(8)]
    try:
        # both learn peers 0-3; only B learns 4-7
        for i, p in enumerate(seeds):
            p.push([b.addr] if i >= 4 else [a.addr, b.addr])
        _settle([a, b] + seeds)
        assert len(a.table) == 4 and len(b.table) == 8
        served_before = b.metrics["pull_served"]
        a.pull(b.addr)
        _settle([a, b])
        assert len(a.table) == 9  # 8 peers + B itself
        # B served the missing records, not A's whole view again
        assert b.metrics["pull_skipped"] >= 4
        assert b.metrics["pull_served"] - served_before <= 5
    finally:
        for n in [a, b] + seeds:
            n.close()


def test_duplicate_pushes_draw_prune_and_stop_forwarding():
    origin = _mk_node(b"origin")
    a, b = _mk_node(b"A"), _mk_node(b"B")
    try:
        # B knows A (needed to address pushes) and the origin's record
        a.push([b.addr])
        origin.push([b.addr])
        _settle([a, b])
        b.refresh_active_set()
        assert a.pubkey in b.active_set
        # B pushes the same origin record to A repeatedly -> A prunes
        for _ in range(b.prune_threshold + 2):
            b._need_push.append(origin.pubkey)
            b.push_round()
            _settle([a, b])
        assert a.metrics["prune_tx"] >= 1
        assert b.metrics["prune_rx"] >= 1
        assert origin.pubkey in b.active_set[a.pubkey][1]
        # next push round drops the pruned origin for A
        before = b.metrics["push_dropped"]
        b._need_push.append(origin.pubkey)
        b.push_round()
        assert b.metrics["push_dropped"] > before
    finally:
        for n in [origin, a, b]:
            n.close()


def test_forged_prune_ignored():
    import hashlib

    from firedancer_tpu.flamenco import gossip_wire as gw

    a, b = _mk_node(b"A2"), _mk_node(b"B2")
    victim = _mk_node(b"victim")
    try:
        a.push([b.addr])
        victim.push([b.addr])
        _settle([a, b])
        b.refresh_active_set()
        # mallory forges a prune "from A" without A's key
        mal_secret = hashlib.sha256(b"mallory").digest()
        pd = gw.prune_make(mal_secret, [victim.pubkey], b.pubkey, 1)
        pd.pubkey = a.pubkey  # claim it is A's prune; signature now wrong
        b.sock.sendto(
            gw.encode_message("prune_message", (a.pubkey, pd)), b.addr
        )
        _settle([b])
        assert b.metrics["prune_rx"] >= 1
        assert not b.active_set.get(a.pubkey, (None, set()))[1]
    finally:
        for n in [a, b, victim]:
            n.close()


def test_stake_weighted_active_set():
    """With a dominant-stake peer, the bounded active set must include
    it (wsample puts the heavy key in essentially every sample)."""
    hub = _mk_node(b"hub")
    peers = [_mk_node(b"w%d" % i) for i in range(10)]
    try:
        for p in peers:
            p.push([hub.addr])
        _settle([hub] + peers)
        assert len(hub.table) == 10
        whale = peers[7].pubkey
        hub.set_stakes({whale: 10_000_000, **{
            p.pubkey: 1 for p in peers if p.pubkey != whale
        }})
        hub.active_size = 3
        hub.refresh_active_set(seed=b"round1")
        assert len(hub.active_set) == 3
        assert whale in hub.active_set
    finally:
        for n in [hub] + peers:
            n.close()


def test_push_round_propagates_fresh_records():
    """Epidemic spread: origin -> A -> (push_round) -> B without B ever
    talking to the origin."""
    origin, a, b = _mk_node(b"o3"), _mk_node(b"A3"), _mk_node(b"B3")
    try:
        b.push([a.addr])  # A knows B
        _settle([a, b])
        a.refresh_active_set()
        origin.push([a.addr])  # A learns origin's record...
        _settle([a, b])
        a.push_round()  # ...and propagates it
        _settle([a, b])
        assert origin.pubkey in b.table
    finally:
        for n in [origin, a, b]:
            n.close()
