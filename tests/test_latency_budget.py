"""Tier-1 latency-budget ratchet (ISSUE 9 / ROADMAP #4).

Drives the REAL flagship pipeline (cooperative form, precomputed verify
so no device compile), with every stage's metrics bound to a live SHM
registry segment — then scrapes those segments back from raw shared
memory, exactly as an uninvolved monitor process would, and fails if any
hop's p50 `frag_latency_ns` regresses past the budgets declared in
runtime/slo.py.  This turns the PR-5 metrics plane into a gate: a stage
silently reverting to per-frag batching or a wedged-open accumulation
deadline shows up HERE, not in the next manual bench round.
"""

from __future__ import annotations

from multiprocessing import shared_memory

import pytest

from firedancer_tpu.runtime.slo import (
    HOP_P50_BUDGET_NS,
    HOP_P99_BUDGET_NS,
    check_hop_budgets,
)
from firedancer_tpu.utils import metrics as fm

N_TXNS = 384


def _scrape(segs, schemas):
    """Fresh attach per segment (the monitor-process view); a helper so
    the registry's numpy views die on return and the segments close."""
    hists = {}
    counters = {}
    for name, seg in segs.items():
        reg, _rec = fm.metrics_segment_attach(seg.buf, schemas[name])
        hists[name] = reg.hist("frag_latency_ns")
        counters[name] = {
            d.name: reg.get(d.name)
            for d in schemas[name].defs if d.kind != fm.HISTOGRAM
        }
        del reg, _rec
    return hists, counters


@pytest.fixture(scope="module")
def scraped_hists():
    """Run the pipeline once with shm-backed registries; yield the
    frag_latency_ns histograms read back from the segments."""
    from firedancer_tpu.models.leader import build_leader_pipeline

    pipe = build_leader_pipeline(
        n_verify=1, n_bank=2, pool_size=N_TXNS, gen_limit=N_TXNS,
        batch=64, max_msg_len=256, verify_precomputed=True,
    )
    segs: dict[str, shared_memory.SharedMemory] = {}
    schemas = {}
    reg = rec = None
    try:
        for s in pipe.stages:
            schema = type(s).metrics_schema()
            seg = shared_memory.SharedMemory(
                create=True, size=fm.metrics_segment_footprint(schema)
            )
            segs[s.name] = seg
            schemas[s.name] = schema
            reg, rec = fm.metrics_segment_init(seg.buf, schema)
            s.attach_observability(reg, rec)
        pipe.run(until_txns=N_TXNS, max_iters=400_000)
        for s in pipe.stages:
            s.metrics.flush()  # the housekeeping publication, forced final
        hists, counters = _scrape(segs, schemas)
        yield {"hists": hists, "counters": counters,
               "native_pack": pipe.dedup is None}
    finally:
        # registries/recorders hold numpy views over seg.buf: drop them
        # (including the setup loop's own locals) before closing or
        # SharedMemory.close raises BufferError
        reg = rec = None
        for s in pipe.stages:
            s.metrics.registry = None
            s.recorder = fm.FlightRecorder(8)
        pipe.close()
        import gc

        gc.collect()
        for seg in segs.values():
            seg.close()
            seg.unlink()


def test_pipeline_carried_traffic(scraped_hists):
    """The budgets only mean something if the hops actually consumed the
    stream: every budgeted hop present in the topology saw frags."""
    counters = scraped_hists["counters"]
    assert counters["pack"]["txn_in"] == N_TXNS
    execs = sum(counters[b]["txn_exec"] for b in ("bank0", "bank1"))
    assert execs == N_TXNS
    hists = scraped_hists["hists"]
    for name in HOP_P50_BUDGET_NS:
        if name in hists and name in counters:
            assert hists[name]["count"] > 0, f"hop {name} observed nothing"


def test_hop_p50s_within_budget(scraped_hists):
    violations = check_hop_budgets(scraped_hists["hists"])
    assert not violations, "latency budget regressions:\n  " + "\n  ".join(
        violations
    )


def test_e2e_budget_declared_and_enforced():
    """The ratchet covers the end-to-end path (the store hop observes
    benchg's tsorig) — guard against the budget table losing that row."""
    assert "store" in HOP_P50_BUDGET_NS
    # and the checker flags an over-budget histogram
    bad = {"store": {"buckets": [1e12], "counts": [0, 5], "sum": 5e12,
                     "count": 5}}
    assert check_hop_budgets(bad)


def test_tail_budget_declared_and_enforced():
    """Round 12: the commit and e2e p99s are budgeted, and the checker
    catches a histogram whose MEDIAN is fine but whose tail blows the
    p99 row (the regression shape a p50-only ratchet is blind to)."""
    assert "bank0" in HOP_P99_BUDGET_NS and "store" in HOP_P99_BUDGET_NS
    # 98 observations at 1ms, 2 in the 10s bucket: p50 passes its
    # budget, p99 lands in the tail bucket and must trip
    bad = {"store": {"buckets": [1e6, 1e10], "counts": [98, 2, 0],
                     "sum": 98e6 + 2e10, "count": 100}}
    msgs = check_hop_budgets(bad)
    assert any("p99" in m for m in msgs), msgs
    assert not any("p50" in m for m in msgs), msgs


def test_tail_hops_observed(scraped_hists):
    """A tail budget on a hop that consumed nothing is dead code — the
    p99-budgeted hops must see the stream in the fixture run (the
    enforcement itself rides test_hop_p50s_within_budget, whose checker
    walks both tables)."""
    hists = scraped_hists["hists"]
    for name in HOP_P99_BUDGET_NS:
        assert name in hists and hists[name]["count"] > 0, name
