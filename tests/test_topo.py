"""Process topology runner tests: stages as real OS processes over shm
links, cnc supervision, watchdog kill on stage death, monitor snapshot.
Mirrors the reference's boot/supervise model (fd_topo_run.c, run.c:252-330)
and the mux IPC script tests (src/disco/mux/test_mux_ipc_*)."""

import time

import pytest

from firedancer_tpu.runtime import topo as ft
from firedancer_tpu.runtime.stage import Stage
from firedancer_tpu.tango import shm
from firedancer_tpu.tango.rings import CNC_SIG_FAIL


class GenStage(Stage):
    def __init__(self, *args, limit=100, **kwargs):
        super().__init__(*args, **kwargs)
        self.limit = limit
        self._i = 0

    def after_credit(self):
        if self._i < self.limit:
            if self.publish(0, b"frag%06d" % self._i, sig=self._i):
                self._i += 1


class RelayStage(Stage):
    def after_frag(self, in_idx, meta, payload):
        self.publish(0, payload, sig=int(meta[1]))


class SinkStage(Stage):
    pass  # counts frags_in via the base metrics/diag export


class CrashStage(Stage):
    def after_frag(self, in_idx, meta, payload):
        if int(meta[1]) >= 10:
            raise RuntimeError("injected stage crash")
        self.publish(0, payload, sig=int(meta[1]))


def build_gen(links, cnc, limit=100):
    return GenStage("gen", outs=[shm.Producer(links["gr"])], cnc=cnc, limit=limit)


def build_relay(links, cnc):
    return RelayStage(
        "relay",
        ins=[shm.Consumer(links["gr"], lazy=8)],
        outs=[shm.Producer(links["rs"])],
        cnc=cnc,
    )


def build_sink(links, cnc):
    return SinkStage("sink", ins=[shm.Consumer(links["rs"], lazy=8)], cnc=cnc)


def build_crash(links, cnc):
    return CrashStage(
        "relay",
        ins=[shm.Consumer(links["gr"], lazy=8)],
        outs=[shm.Producer(links["rs"])],
        cnc=cnc,
    )


N = 200


def test_three_process_topology_end_to_end():
    topo = ft.Topology()
    topo.link("gr", depth=256, mtu=64)
    topo.link("rs", depth=256, mtu=64)
    topo.stage("gen", build_gen, limit=N)
    topo.stage("relay", build_relay)
    topo.stage("sink", build_sink)
    h = ft.launch(topo)
    try:
        ok = h.supervise(
            until=lambda h: h.cncs["sink"].diag(Stage.DIAG_FRAGS_IN) >= N,
            timeout_s=60,
        )
        assert ok, f"supervisor failed (failed stage: {h.failed})"
        # diag counters flush on lazy housekeeping ticks (fd_cnc model):
        # the monitor may lag the data plane by one interval — poll for
        # convergence instead of snapshotting the race
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            snap = {r["stage"]: r for r in h.snapshot()}
            if (
                snap["gen"]["frags_out"] == N
                and snap["relay"]["frags_in"] == N
                and snap["relay"]["frags_out"] == N
            ):
                break
            time.sleep(0.05)
        assert snap["gen"]["frags_out"] == N
        assert snap["relay"]["frags_in"] == N
        assert snap["relay"]["frags_out"] == N
        assert snap["sink"]["frags_in"] >= N
        assert all(r["alive"] for r in snap.values())
        mon = h.format_monitor()
        assert "sink" in mon and str(N) in mon
        h.halt()
        assert all(not p.is_alive() for p in h.procs.values())
        assert all(p.exitcode == 0 for p in h.procs.values())
    finally:
        h.close()


def test_watchdog_kills_topology_on_stage_crash():
    topo = ft.Topology()
    topo.link("gr", depth=256, mtu=64)
    topo.link("rs", depth=256, mtu=64)
    topo.stage("gen", build_gen, limit=N)
    topo.stage("relay", build_crash)
    topo.stage("sink", build_sink)
    h = ft.launch(topo)
    try:
        ok = h.supervise(
            until=lambda h: h.cncs["sink"].diag(Stage.DIAG_FRAGS_IN) >= N,
            timeout_s=60,
        )
        assert not ok, "supervisor should have detected the crash"
        assert h.failed == "relay"
        # crash containment: the WHOLE topology is down (run.c:252-330)
        assert all(not p.is_alive() for p in h.procs.values())
        assert h.cncs["relay"].signal == CNC_SIG_FAIL
    finally:
        h.close()


def test_supervise_detects_missing_heartbeat():
    """A stage that never boots (builder hangs) trips the heartbeat
    watchdog rather than wedging the parent."""

    topo = ft.Topology()
    topo.link("gr", depth=256, mtu=64)
    topo.stage("gen", build_gen, limit=N)
    topo.stage("hang", _build_hang)
    h = ft.launch(topo)
    try:
        t0 = time.monotonic()
        ok = h.supervise(timeout_s=30, heartbeat_timeout_s=1.0, until=lambda h: False)
        assert not ok
        assert h.failed == "hang"
        assert time.monotonic() - t0 < 25
    finally:
        h.close()


def _build_hang(links, cnc):
    cnc.heartbeat(time.monotonic_ns())  # one beat, then wedge
    time.sleep(3600)
