"""Logging + schema metrics tests (util/log and disco/metrics analogs)."""

import numpy as np
import pytest

from firedancer_tpu.utils import log as fl
from firedancer_tpu.utils import metrics as fm


# -- logging ------------------------------------------------------------------


def test_log_two_streams(tmp_path, capsys):
    path = str(tmp_path / "fd.log")
    fl.init(path=path, stderr_level=fl.NOTICE, file_level=fl.INFO)
    log = fl.get_logger("teststage")
    log.debug("invisible everywhere")
    log.info("file only")
    log.notice("both streams")
    err = capsys.readouterr().err
    assert "both streams" in err
    assert "file only" not in err
    content = open(path).read()
    assert "file only" in content and "both streams" in content
    assert "invisible everywhere" not in content
    assert "teststage" in content


def test_log_err_raises(tmp_path):
    fl.init(path="", raise_on_err=True)
    log = fl.get_logger("x")
    with pytest.raises(fl.LogError):
        log.err("fatal condition")
    fl.init(raise_on_err=False)
    log.err("tolerated in supervisor tests")
    fl.init(raise_on_err=True)


# -- metrics ------------------------------------------------------------------


def test_counters_and_gauges():
    schema = fm.MetricsSchema().counter("a", "help a").gauge("g")
    reg = fm.MetricsRegistry(schema)
    reg.inc("a")
    reg.inc("a", 5)
    reg.set("g", 42)
    assert reg.get("a") == 6
    assert reg.get("g") == 42
    with pytest.raises(TypeError):
        reg.set("a", 1)


def test_histogram_buckets_and_quantile():
    schema = fm.MetricsSchema().histogram("lat", [10, 100, 1000])
    reg = fm.MetricsRegistry(schema)
    for v in [1, 5, 50, 500, 5000, 50000]:
        reg.observe("lat", v)
    h = reg.hist("lat")
    assert h["counts"] == [2, 1, 1, 2]  # <=10, <=100, <=1000, +Inf
    assert h["count"] == 6
    assert h["sum"] == 55556
    assert reg.quantile("lat", 0.5) == 100
    assert reg.quantile("lat", 0.99) == float("inf")


def test_registry_over_shared_buffer():
    """The monitor-reads-producer-memory property: two registries over one
    buffer see each other's writes (fd_metrics shm array)."""
    schema = fm.stage_schema()
    buf = np.zeros(schema.footprint(), dtype=np.uint64)
    producer = fm.MetricsRegistry(schema, buf=buf)
    monitor = fm.MetricsRegistry(schema, buf=buf)
    producer.inc("frags_in", 7)
    producer.observe("frag_latency_ns", 5e5)
    assert monitor.get("frags_in") == 7
    assert monitor.hist("frag_latency_ns")["count"] == 1


def test_prometheus_exposition():
    schema = fm.MetricsSchema().counter("txn_total", "txns").histogram(
        "lat_ns", [10.0, 100.0]
    )
    r1 = fm.MetricsRegistry(schema)
    r2 = fm.MetricsRegistry(schema)
    r1.inc("txn_total", 3)
    r2.inc("txn_total", 4)
    r1.observe("lat_ns", 50)
    text = fm.render_prometheus({"verify0": r1, "verify1": r2})
    assert '# TYPE txn_total counter' in text
    assert 'txn_total{stage="verify0"} 3' in text
    assert 'txn_total{stage="verify1"} 4' in text
    assert 'lat_ns_bucket{stage="verify0",le="100.0"} 1' in text
    assert 'lat_ns_count{stage="verify0"} 1' in text
    # HELP/TYPE emitted once per metric, not per stage
    assert text.count("# TYPE txn_total counter") == 1


def test_histogram_fractional_and_negative_sum():
    """Regression (ISSUE 5 satellite): observe() used to truncate each
    observation via int(), so sub-unit values (ms-denominated latencies)
    summed to 0 and negatives silently corrupted the sum.  The sum word
    now stores value * SUM_SCALE rounded; hist() divides back out."""
    schema = fm.MetricsSchema().histogram("lat_ms", [1.0, 10.0])
    reg = fm.MetricsRegistry(schema)
    reg.observe("lat_ms", 0.5)
    reg.observe("lat_ms", 0.25)
    h = reg.hist("lat_ms")
    assert h["count"] == 2
    assert h["sum"] == pytest.approx(0.75, abs=2 / fm.SUM_SCALE)
    # negatives clamp to zero contribution (first bucket, nothing summed)
    reg.observe("lat_ms", -5.0)
    h = reg.hist("lat_ms")
    assert h["count"] == 3 and h["counts"][0] == 3
    assert h["sum"] == pytest.approx(0.75, abs=2 / fm.SUM_SCALE)
    # integer-valued observations stay exact (the pre-fix contract)
    reg2 = fm.MetricsRegistry(schema)
    for v in (1, 2, 3):
        reg2.observe("lat_ms", v)
    assert reg2.hist("lat_ms")["sum"] == 6


def test_prometheus_escaping_hostile_names():
    """Stage names and help strings are interpolated into the exposition
    format: backslash, quote and newline must escape per the text-format
    spec or a hostile name injects fake series."""
    schema = fm.MetricsSchema().counter(
        "txn_total", 'has "quotes" and \\slashes\nand newlines'
    ).histogram("lat", [1.0])
    reg = fm.MetricsRegistry(schema)
    reg.inc("txn_total", 3)
    reg.observe("lat", 0.5)
    hostile = 'st"age\\one\ninjected_metric 999'
    text = fm.render_prometheus({hostile: reg})
    # one logical line per metric sample: the newline never leaks raw
    assert "injected_metric 999\n" not in text.replace("\\n", "")
    for ln in text.splitlines():
        assert not ln.startswith("injected_metric")
    assert 'stage="st\\"age\\\\one\\ninjected_metric 999"' in text
    assert "# HELP txn_total" in text
    help_line = [ln for ln in text.splitlines()
                 if ln.startswith("# HELP txn_total")][0]
    assert "\\\\slashes" in help_line and "\\n" in help_line
    # histogram label lines escape the same way
    assert 'lat_bucket{stage="st\\"age\\\\one\\ninjected_metric 999",le="1.0"}' in text


def test_prometheus_http_endpoint():
    """The metric-tile analog: live registries scraped over HTTP."""
    import urllib.request

    schema = fm.MetricsSchema().counter("txn_total")
    reg = fm.MetricsRegistry(schema)
    srv = fm.MetricsServer({"verify0": reg})
    try:
        host, port = srv.addr
        reg.inc("txn_total", 5)
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10
        ).read().decode()
        assert 'txn_total{stage="verify0"} 5' in body
        # live: a later scrape sees new values
        reg.inc("txn_total", 2)
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10
        ).read().decode()
        assert 'txn_total{stage="verify0"} 7' in body
        # unknown path 404s
        import urllib.error

        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{host}:{port}/nope", timeout=10)
    finally:
        srv.close()


# -- util rng -----------------------------------------------------------------


def test_rng_deterministic_and_distinct_streams():
    from firedancer_tpu.utils.rng import Rng

    a, b = Rng(7, 0), Rng(7, 0)
    assert [a.ulong() for _ in range(100)] == [b.ulong() for _ in range(100)]
    # distinct (seq, idx) pairs give distinct streams — including every
    # aliasing family earlier constructions fell to: shift-xor ((1,0) vs
    # (0,2)), the seq <-> ~idx symmetry, and complement-pair degeneracy
    M = (1 << 64) - 1
    pairs = [
        (7, 0), (7, 1), (1, 0), (0, 2), (0, 0), (2**63, 0),
        (0, M), (1, M - 1), (5, ~5 & M), (M, M),
    ]
    streams = {p: tuple(Rng(*p).ulong() for _ in range(5)) for p in pairs}
    assert len(set(streams.values())) == len(streams)
    # and no degenerate near-zero stream
    assert all(max(s) > 1 << 32 for s in streams.values())


def test_rng_roll_and_float():
    from firedancer_tpu.utils.rng import Rng

    r = Rng(3)
    vals = [r.roll(10) for _ in range(5000)]
    assert set(vals) == set(range(10))
    counts = [vals.count(k) for k in range(10)]
    assert min(counts) > 350  # rough uniformity
    fs = [r.float01() for _ in range(1000)]
    assert all(0.0 <= f < 1.0 for f in fs)
    xs = r.shuffle(list(range(50)))
    assert sorted(xs) == list(range(50)) and xs != list(range(50))
