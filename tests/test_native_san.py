"""Sanitizer lane (ISSUE 15, TSan twin PR 17): the native differential
suites under ASan/UBSan/TSan-instrumented .so's.

The point: the C++ hot paths (~5k LoC across 9 translation units) had
zero sanitizer coverage — PR 10's review history (NULL-deref guards,
SIGFPE guard, range checks found only by hand) is exactly the class an
instrumented run catches mechanically.  `FDTPU_NATIVE_SAN=asan|ubsan`
makes utils/nativebuild build+load instrumented twins from
native/san/<san>/, so the SAME differential suites (ring, pack, shred,
verify, exec + the txn/tcache support bindings) exercise the SAME
binding surface — any heap overflow, use-after-free, shift/overflow UB
or misaligned access in a crossing aborts the run.

ASan's runtime must be the first DSO in the process, so the suites run
in a SUBPROCESS with nativebuild.san_env()'s LD_PRELOAD overlay; leak
detection stays off (CPython deliberately leaks at exit).  The full
matrix rides the slow marker (CI's san-smoke job runs it with
FDTPU_SLOW=1); the redirection mechanics are tier-1-cheap and tested
inline.  Findings get FIXED in the C++, never suppressed — the PR 2
fix-the-true-positives precedent.
"""

import os
import shutil
import subprocess
import sys

import pytest

from firedancer_tpu.utils import nativebuild as nb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the native differential suites: every .so crossing has one
SAN_SUITES = (
    "test_native_ring.py",    # ring plane (fd_ring)
    "test_txn_native.py",     # parser (fd_txn_parse)
    "test_tcache_native.py",  # dedup structure (fd_tcache)
    "test_pack_native.py",    # pack scheduler + fused dedup (fd_pack)
    "test_shred_native.py",   # shredder + reedsol (fd_shred, fd_reedsol)
    "test_verify_native.py",  # verify sweep client (fd_verify)
    "test_exec_native.py",    # executor fast lane (fd_exec_native)
    "test_bank_native.py",    # bank sweep client + result log (fd_bank)
    "test_net_native.py",     # net sweep client + QUIC fast path (fd_net)
    "test_funk_native.py",    # shm storage plane (fd_funk)
)


def _san_env(san: str) -> dict | None:
    """Full subprocess env for a sanitized run, or None to skip."""
    if shutil.which("g++") is None:
        return None
    try:
        overlay = nb.san_env(san)
    except nb.NativeUnavailable:
        return None
    env = {**os.environ, **overlay, "JAX_PLATFORMS": "cpu"}
    env.pop("FDTPU_SLOW", None)  # the inner run is the quick tier
    return env


# -- tier-1-cheap mechanics ---------------------------------------------------


def test_san_mode_validates_and_redirects(monkeypatch, tmp_path):
    monkeypatch.delenv(nb.SAN_ENV, raising=False)
    assert nb.san_mode() is None
    monkeypatch.setenv(nb.SAN_ENV, "asan")
    assert nb.san_mode() == "asan"
    monkeypatch.setenv(nb.SAN_ENV, "msan")  # unsupported: hard error
    with pytest.raises(nb.NativeUnavailable):
        nb.san_mode()
    assert nb.san_so_path("/x/native/fd_ring.so", "ubsan") == \
        "/x/native/san/ubsan/fd_ring.so"


def test_build_so_returns_san_twin(monkeypatch, tmp_path):
    """The contract every loader now relies on: build_so returns the
    path it built, and under the san lane that is the instrumented
    twin, not the caller's `so` argument."""
    if shutil.which("g++") is None:
        pytest.skip("no toolchain")
    src = tmp_path / "t.cpp"
    src.write_text('extern "C" { int forty_two() { return 42; } }\n')
    so = tmp_path / "t.so"
    monkeypatch.delenv(nb.SAN_ENV, raising=False)
    assert nb.build_so(str(src), str(so)) == str(so)
    monkeypatch.setenv(nb.SAN_ENV, "ubsan")
    twin = nb.build_so(str(src), str(so))
    assert twin == str(tmp_path / "san" / "ubsan" / "t.so")
    assert os.path.exists(twin)


# -- the differential matrix --------------------------------------------------


def _run_suites(san: str) -> None:
    env = _san_env(san)
    if env is None:
        pytest.skip(f"no toolchain/{san} runtime on this host")
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-m", "not slow",
         "-p", "no:cacheprovider",
         *[os.path.join(REPO, "tests", s) for s in SAN_SUITES]],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=3000,
    )
    assert r.returncode == 0, (
        f"{san} differential run failed (rc={r.returncode}):\n"
        f"{r.stdout[-8000:]}\n{r.stderr[-8000:]}"
    )
    # belt and braces: a sanitizer abort mid-collection can still exit 0
    # on some pytest paths — the report text must show real passes and
    # carry no sanitizer report anywhere in the output
    assert " passed" in r.stdout, r.stdout[-2000:]
    blob = r.stdout + r.stderr
    assert "ERROR: AddressSanitizer" not in blob, blob[-4000:]
    assert "runtime error:" not in blob, blob[-4000:]  # UBSan report line
    assert "WARNING: ThreadSanitizer" not in blob, blob[-4000:]


@pytest.mark.slow
def test_asan_differential_suites():
    _run_suites("asan")


@pytest.mark.slow
def test_ubsan_differential_suites():
    _run_suites("ubsan")


@pytest.mark.slow
def test_tsan_differential_suites():
    """TSan twin (PR 17): the same differential matrix over
    -fsanitize=thread builds.  TSan models in-process threads only —
    the cross-process shm rings are outside it (the static FD406 pass
    in analysis/race_check covers those fences; docs/OPERATIONS.md
    explains why a TSan report against an mmap'd ring cell is an
    artifact) — so this leg guards the threaded native paths and
    proves the instrumented .so's stay report-clean under load."""
    _run_suites("tsan")
