"""ChaCha20 block + protocol RNG + weighted sampling + leader schedule.

Pinned to public vectors: the RFC 7539 2.3.2 block vector, and the
rand_chacha stream values the reference also requires
(test_chacha20rng.c: first u64 and the u64 after 100001 reads)."""

import numpy as np
import pytest

from firedancer_tpu.ops import chacha20 as cc
from firedancer_tpu.protocol import wsample as ws

RFC_KEY = bytes(range(32))
RFC_NONCE = bytes([0, 0, 0, 9, 0, 0, 0, 0x4A, 0, 0, 0, 0])
RFC_BLOCK1 = bytes.fromhex(
    "10f1e7e4d13b5915500fdd1fa32071c4"
    "c7d1f4c733c068030422aa9ac3d46c4e"
    "d2826446079faa0914c2d705d98b02a2"
    "b5129cd1de164eb9cbd083e8a2503c4e"
)


def test_block_host_rfc7539():
    assert cc.chacha20_block_host(RFC_KEY, 1, RFC_NONCE) == RFC_BLOCK1


def test_keystream_device_matches_host():
    rng = np.random.default_rng(2)
    b = 5
    keys = rng.integers(0, 256, (32, b), dtype=np.int32)
    nonces = rng.integers(0, 256, (12, b), dtype=np.int32)
    idxs = np.asarray([0, 1, 2, 7, 1000], dtype=np.int32)
    out = np.asarray(cc.chacha20_keystream(keys, idxs, nonces))
    for i in range(b):
        expect = cc.chacha20_block_host(
            keys[:, i].astype(np.uint8).tobytes(),
            int(idxs[i]),
            nonces[:, i].astype(np.uint8).tobytes(),
        )
        assert out[:, i].astype(np.uint8).tobytes() == expect


def test_rng_rand_chacha_stream():
    rng = cc.ChaCha20Rng(RFC_KEY, mode=cc.MODE_MOD)
    assert rng.ulong() == 0x6A19C5D97D2BFD39
    for _ in range(100_000):
        rng.ulong()
    assert rng.ulong() == 0xF4682B7E28EAE4A7


def test_roll_ranges_and_determinism():
    for mode in (cc.MODE_MOD, cc.MODE_SHIFT):
        rng = cc.ChaCha20Rng(b"\x07" * 32, mode=mode)
        vals = [rng.ulong_roll(10) for _ in range(2000)]
        assert all(0 <= v < 10 for v in vals)
        assert len(set(vals)) == 10  # all residues hit
        # deterministic for a fixed seed
        rng2 = cc.ChaCha20Rng(b"\x07" * 32, mode=mode)
        assert [rng2.ulong_roll(10) for _ in range(2000)] == vals
    # the two modes reject differently -> different streams
    a = cc.ChaCha20Rng(b"\x09" * 32, mode=cc.MODE_MOD)
    b = cc.ChaCha20Rng(b"\x09" * 32, mode=cc.MODE_SHIFT)
    assert [a.ulong_roll(7) for _ in range(100)] != [
        b.ulong_roll(7) for _ in range(100)
    ]


def test_wsample_distribution_and_removal():
    rng = cc.ChaCha20Rng(b"\x01" * 32)
    w = ws.WSample(rng, [90, 9, 1])
    counts = [0, 0, 0]
    for _ in range(3000):
        counts[w.sample()] += 1
    assert counts[0] > counts[1] > counts[2] > 0
    assert counts[0] > 2500  # ~90%
    # removal: each index exactly once, then EMPTY
    rng = cc.ChaCha20Rng(b"\x02" * 32)
    w = ws.WSample(rng, [5, 5, 5, 5])
    got = sorted(w.sample_and_remove_many(4))
    assert got == [0, 1, 2, 3]
    assert w.sample_and_remove() == ws.EMPTY


def test_wsample_excluded_poisons():
    # excluded weight dominates: the first roll that lands in the excluded
    # tail returns INDETERMINATE and poisons removal-mode sampling
    rng = cc.ChaCha20Rng(b"\x03" * 32)
    w = ws.WSample(rng, [1], excluded_weight=1 << 40)
    assert w.sample_and_remove() == ws.INDETERMINATE
    assert w.poisoned
    assert w.sample_and_remove() == ws.INDETERMINATE
    # no-removal mode: INDETERMINATE rolls don't poison
    rng = cc.ChaCha20Rng(b"\x04" * 32)
    w = ws.WSample(rng, [1 << 40], excluded_weight=1)
    vals = {w.sample() for _ in range(50)}
    assert vals == {0} or ws.INDETERMINATE in vals and 0 in vals


def test_epoch_leaders_schedule():
    stakes = [
        (b"A" * 32, 4_000_000),
        (b"B" * 32, 2_000_000),
        (b"C" * 32, 1_000_000),
    ]
    lead = ws.epoch_leaders(epoch=7, slot0=1000, slot_cnt=80, stakes=stakes)
    assert len(lead.sched) == 20  # 80 slots / 4 per rotation
    # leader constant within a rotation
    for r in range(20):
        slot = 1000 + r * 4
        leaders = {lead.leader_for_slot(slot + i) for i in range(4)}
        assert len(leaders) == 1
    # deterministic in epoch
    again = ws.epoch_leaders(epoch=7, slot0=1000, slot_cnt=80, stakes=stakes)
    assert again.sched == lead.sched
    other = ws.epoch_leaders(epoch=8, slot0=1000, slot_cnt=80, stakes=stakes)
    assert other.sched != lead.sched
    # out of range
    assert lead.leader_for_slot(999) is None
    assert lead.leader_for_slot(1080) is None
    # stake-weighted: A leads most rotations over a bigger schedule
    big = ws.epoch_leaders(epoch=3, slot0=0, slot_cnt=4000, stakes=stakes)
    from collections import Counter

    c = Counter(big.sched)
    assert c[0] > c[1] > c[2]
