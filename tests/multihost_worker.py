"""Worker for the two-process jax.distributed smoke test (SURVEY §5.8's
DCN story run for real: coordinator handshake, Gloo cross-process
collectives on the CPU backend).  Launched by test_multihost_2proc."""

import sys

sys.path.insert(0, ".")

from firedancer_tpu.utils.platform import force_cpu_backend

force_cpu_backend(device_count=4)

import numpy as np

from firedancer_tpu.parallel import multihost as mh


def main(coordinator: str, rank: int) -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    topo = mh.initialize(coordinator=coordinator, num_processes=2,
                         process_id=rank)
    assert topo.num_hosts == 2 and topo.host_id == rank
    assert jax.process_count() == 2
    assert jax.device_count() == 8
    assert jax.local_device_count() == 4

    # flat mesh: a cross-host psum over all 8 devices
    mesh = mh.global_mesh()
    f = jax.jit(
        jax.shard_map(lambda x: jax.lax.psum(x, "verify"), mesh=mesh,
                      in_specs=P("verify"), out_specs=P()),
        in_shardings=NamedSharding(mesh, P("verify")),
    )
    xs = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("verify")),
        np.full((8,), rank + 1, np.float32),
    )
    # local halves are [1,1,..] and [2,2,..] -> psum = 4*1 + 4*2 = 12
    out = np.asarray(f(xs))
    assert np.all(out == 12.0), out

    # host-tiled mesh: reduce within the host (ICI axis), then across
    # hosts (DCN axis) — the sharded-verify reduction shape
    tiled = mh.host_tiled_mesh()
    assert tiled.devices.shape == (2, 4)
    g = jax.jit(
        jax.shard_map(
            lambda x: jax.lax.psum(jax.lax.psum(x, "verify"), "host"),
            mesh=tiled, in_specs=P("host", "verify"), out_specs=P(),
        ),
        in_shardings=NamedSharding(tiled, P("host", "verify")),
    )
    ys = jax.make_array_from_process_local_data(
        NamedSharding(tiled, P("host", "verify")),
        np.ones((1, 8), np.float32),
    )
    # 8 one-filled device blocks reduce elementwise: 4 (verify) x 2 (host)
    assert np.all(np.asarray(g(ys)) == 8.0)

    # every host derives the SAME shard split from the topology
    assert mh.shard_counts(topo, 16387) == [8194, 8193]
    print(f"RANK{rank} OK", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]))
