"""Differential suite: the native net lane vs the Python lane (ISSUE 18).

Every test drives the SAME client traffic into two QuicIngressStages —
one with the native fast path armed, one pinned to the Python lane via
FDTPU_NATIVE_NET=0 — and diffs the published txn streams byte-for-byte.
The PUNT boundary (handshakes, stateless resets, control frames) and the
credit-gated no-loss/no-reorder contract get their own tests, plus a
seeded AES-GCM fuzz parity pass against ops/aes.py (incl. tag rejects).

The module skips entirely when the .so cannot build or the lane is
disabled (FDTPU_NATIVE_NET=0): differential claims need both lanes.
"""

from __future__ import annotations

import hashlib
import os

import pytest

from firedancer_tpu.runtime import net_native

pytestmark = pytest.mark.skipif(
    not net_native.available(),
    reason="fd_net.so unavailable or FDTPU_NATIVE_NET=0",
)

IDENTITY = hashlib.sha256(b"net-native-diff").digest()


class _Collector:
    """Producer stub: records every published frame; optional credit
    budget for the backpressure tests (None = unlimited)."""

    def __init__(self, credits=None):
        self.frames = []  # (payload, sig)
        self.credits = credits

    def try_publish(self, payload, sig=0, tsorig=0):
        if self.credits is not None:
            if self.credits <= 0:
                return False
            self.credits -= 1
        self.frames.append((bytes(payload), sig))
        return True

    def payloads(self):
        return [p for p, _ in self.frames]


def _make_stage(native: bool, monkeypatch, **kw):
    from firedancer_tpu.chaos.population import ChaosSock
    from firedancer_tpu.runtime.net import QuicIngressStage

    monkeypatch.setenv("FDTPU_NATIVE_NET", "1" if native else "0")
    st = QuicIngressStage(
        "quic", outs=[kw.pop("out", None) or _Collector()],
        sock=ChaosSock(), rx_burst=8, identity_secret=IDENTITY, **kw)
    assert (st._net_client is not None) == native
    return st


class _Driver:
    """In-process QUIC client against a ChaosSock'd stage: datagrams are
    injected straight into _on_datagram, responses read back off the
    virtual socket — the chaos population's wire, without loss."""

    def __init__(self, stage, addr, *, mangle=None):
        from firedancer_tpu.ops.ref import ed25519_ref as ref
        from firedancer_tpu.waltz import quic

        self.stage = stage
        self.addr = addr
        self.mangle = mangle  # fn(datagram) -> datagram(s) to inject
        self.conn = quic.Connection.client_new(
            expected_peer=ref.public_key(IDENTITY))
        self.next_sid = 2
        self.pump()
        assert self.conn.established

    def _inject(self, dg: bytes) -> None:
        dgs = [dg] if self.mangle is None else self.mangle(dg)
        for d in dgs:
            self.stage._on_datagram(d, self.addr)

    def pump(self, rounds: int = 40) -> None:
        for _ in range(rounds):
            moved = False
            for dg in self.conn.flush():
                moved = True
                self._inject(dg)
            q = self.stage.sock.tx.get(self.addr)
            while q:
                moved = True
                self.conn.receive(q.popleft())
            if not moved:
                return

    def send_txn(self, txn: bytes) -> None:
        sid = self.next_sid
        self.next_sid += 4
        self.conn.send_stream(sid, txn, fin=True)
        self.pump()


def _txn_set(seed: bytes, sizes=(1, 96, 512, 900, 1232)) -> list[bytes]:
    out = []
    for i, n in enumerate(sizes):
        h = hashlib.sha256(seed + bytes([i]))
        buf = b""
        while len(buf) < n:
            h = hashlib.sha256(h.digest() + seed)
            buf += h.digest()
        out.append(buf[:n])
    return out


def _run_both(monkeypatch, drive, **stage_kw):
    """drive(stage, collector) on a native and a Python-lane stage;
    returns both collectors."""
    outs = []
    for native in (True, False):
        out = _Collector()
        st = _make_stage(native, monkeypatch, out=out, **stage_kw)
        drive(st, out)
        st.close()
        outs.append(out)
    return outs


# -- stream diffs -------------------------------------------------------------


def test_honest_streams_byte_identical(monkeypatch):
    txns = _txn_set(b"honest")

    def drive(st, out):
        d = _Driver(st, ("c", 1))
        for t in txns:
            d.send_txn(t)
        st.after_credit()

    on, off = _run_both(monkeypatch, drive)
    assert on.payloads() == txns
    assert on.frames == off.frames  # payloads AND sig sequence


def test_garbled_datagrams_rejected_identically(monkeypatch):
    """Every steady-state datagram is duplicated with one flipped
    ciphertext byte: the mangled twin must fail auth on both lanes
    while the honest stream stays byte-identical."""
    txns = _txn_set(b"garble", sizes=(64, 700, 1232))
    stats = []

    def drive(st, out):
        def mangle(dg):
            if dg[0] & 0x80:
                return [dg]  # leave the handshake alone
            bad = bytearray(dg)
            bad[-1] ^= 0x5A
            return [bytes(bad), dg]

        d = _Driver(st, ("c", 1), mangle=mangle)
        for t in txns:
            d.send_txn(t)
        st.after_credit()
        stats.append(st.metrics.get("bad_packet"))

    on, off = _run_both(monkeypatch, drive)
    assert on.payloads() == txns
    assert on.frames == off.frames
    assert stats[0] == stats[1] > 0
    # and the native lane's verdicts were its own, not punts
    assert stats[0] >= 1


def test_duplicate_datagrams_deliver_once(monkeypatch):
    txns = _txn_set(b"dup", sizes=(96, 1100))

    def drive(st, out):
        d = _Driver(st, ("c", 1), mangle=lambda dg: [dg, dg])
        for t in txns:
            d.send_txn(t)
        st.after_credit()

    on, off = _run_both(monkeypatch, drive)
    assert on.payloads() == txns
    assert on.frames == off.frames


def test_oversize_stream_tombstoned_on_both_lanes(monkeypatch):
    """A stream past TXN_MTU publishes nothing anywhere; honest streams
    around it are unaffected."""
    good = _txn_set(b"oversz-good", sizes=(96, 1232))

    def drive(st, out):
        d = _Driver(st, ("c", 1))
        d.send_txn(good[0])
        sid = d.next_sid
        d.next_sid += 4
        d.conn.send_stream(sid, b"\xAA" * 2000, fin=True)
        d.pump()
        d.send_txn(good[1])
        st.after_credit()

    on, off = _run_both(monkeypatch, drive)
    assert on.payloads() == good
    assert on.frames == off.frames


def test_unknown_cid_stateless_reset_parity(monkeypatch):
    """Short header, unknown address, unknown CID: both lanes answer
    with a stateless reset committing to the SAME token (the datagram's
    random padding differs by design; the token is the commitment)."""
    from firedancer_tpu.waltz import quic

    dg = b"\x40" + b"\x77" * 8 + os.urandom(40)  # >= 43 bytes
    tokens = []

    def drive(st, out):
        st._on_datagram(dg, ("stranger", 9))
        q = st.sock.tx.get(("stranger", 9))
        assert q and len(q) == 1
        reset = q.popleft()
        assert not reset[0] & 0x80
        tokens.append(bytes(reset[-16:]))
        assert st.metrics.get("stateless_reset_tx") == 1

    _run_both(monkeypatch, drive)
    expect = quic.stateless_reset_token(
        hashlib.sha256(b"quic-static:" + IDENTITY).digest(), b"\x77" * 8)
    assert tokens[0] == tokens[1] == expect


# -- PUNT boundary ------------------------------------------------------------


def test_handshake_mid_stream_punts_cleanly(monkeypatch):
    """A second client handshakes (long headers -> PUNT) while the first
    streams through the native fast path; both clients' txns arrive, in
    their own order, identically on both lanes."""
    txns_a = _txn_set(b"mid-a", sizes=(200, 800))
    txns_b = _txn_set(b"mid-b", sizes=(96,))

    def drive(st, out):
        da = _Driver(st, ("a", 1))
        da.send_txn(txns_a[0])
        db = _Driver(st, ("b", 2))  # handshake mid-stream
        da.send_txn(txns_a[1])
        db.send_txn(txns_b[0])
        st.after_credit()

    on, off = _run_both(monkeypatch, drive)
    assert on.payloads() == [txns_a[0], txns_a[1], txns_b[0]]
    assert on.frames == off.frames


def test_control_frame_splice_keeps_conn_coherent(monkeypatch):
    """PATH_CHALLENGE probes (native PUNT) spliced between short-header
    stream datagrams (native consume) on ONE conn: the punted packets'
    pns must land in the native dedup window and the PATH_RESPONSEs must
    come back — the mixed-lane conn stays fully coherent."""
    from firedancer_tpu.waltz import quic

    txns = _txn_set(b"splice", sizes=(96, 600, 1232))

    def drive(st, out):
        d = _Driver(st, ("c", 1))
        for i, t in enumerate(txns):
            probe = d.conn.probe_datagram(
                bytes([quic.FT_PATH_CHALLENGE]) + bytes([i]) * 8)
            assert probe is not None
            st._on_datagram(probe, d.addr)
            d.pump()
            d.send_txn(t)
        st.after_credit()
        d.pump()
        # PATH_RESPONSE echoes arrived back at the client conn
        # (the Python control plane answered the punted frames)
        assert st.metrics.get("pkt_rx") > 0

    on, off = _run_both(monkeypatch, drive)
    assert on.payloads() == txns
    assert on.frames == off.frames


def test_punted_pns_are_deduped_natively(monkeypatch):
    """Replaying a punted control datagram must not double-process it:
    the punt-path pn sync keeps the native window honest."""
    from firedancer_tpu.waltz import quic

    st = _make_stage(True, monkeypatch)
    d = _Driver(st, ("c", 1))
    probe = d.conn.probe_datagram(
        bytes([quic.FT_PATH_CHALLENGE]) + b"\x11" * 8)
    st._on_datagram(probe, d.addr)
    before = st.net_counters()["dup"]
    st._on_datagram(probe, d.addr)  # replay: now short-header + known pn
    assert st.net_counters()["dup"] == before + 1
    st.close()


# -- backpressure: queued, never dropped, never reordered ---------------------


def test_backpressure_native_tail_queued_no_loss_no_reorder(monkeypatch):
    txns = _txn_set(b"bp", sizes=(96, 96, 96, 96, 96, 96))
    out = _Collector(credits=2)
    st = _make_stage(True, monkeypatch, out=out)
    d = _Driver(st, ("c", 1))
    for t in txns:
        d.send_txn(t)
    assert len(out.frames) == 2
    assert st.metrics.get("txn_drop_backpressure") > 0
    assert st.net_counters()["tail_retained"] > 0
    out.credits = None  # lift the gate; after_credit retries the tail
    st.after_credit()
    assert out.payloads() == txns  # nothing lost, nothing reordered
    sigs = [s for _, s in out.frames]
    assert sigs == list(range(1, len(txns) + 1))  # stable across retries
    st.close()


# -- AES-GCM fuzz parity ------------------------------------------------------


def _py_lane_aes(monkeypatch):
    from firedancer_tpu.ops import aes
    monkeypatch.setattr(aes, "_NATIVE", False)
    return aes


def test_aes_gcm_fuzz_parity(monkeypatch):
    """Seeded seal/open fuzz: native vs pure-Python ops/aes.py over both
    key sizes, ragged lengths, and tag-mismatch rejects."""
    aes = _py_lane_aes(monkeypatch)
    rng = hashlib.sha256(b"aes-fuzz")

    def take(n):
        nonlocal rng
        buf = b""
        while len(buf) < n:
            rng = hashlib.sha256(rng.digest())
            buf += rng.digest()
        return buf[:n]

    for trial in range(40):
        klen = 16 if trial % 2 == 0 else 32
        key, iv = take(klen), take(12)
        pt = take(trial * 37 % 1400)
        aad = take(trial * 11 % 64)
        g = aes.AesGcm(key)
        ct, tag = g.seal(iv, pt, aad)
        assert net_native.gcm_seal(key, iv, pt, aad) == (ct, tag)
        assert net_native.gcm_open(key, iv, ct, tag, aad) == pt
        bad = bytes([tag[0] ^ 1]) + tag[1:]
        assert net_native.gcm_open(key, iv, ct, bad, aad) is None
        assert g.open(iv, ct, bad, aad) is None
        if pt:
            bad_ct = bytes([ct[0] ^ 1]) + ct[1:]
            assert net_native.gcm_open(key, iv, bad_ct, tag, aad) is None
        blk = take(16)
        assert net_native.aes_ecb_blocks(key, blk) == \
            aes.Aes(key).encrypt_block(blk)


def test_aes_bad_key_length_rejected():
    with pytest.raises(ValueError):
        net_native.aes_ecb_blocks(b"short", b"\x00" * 16)
    with pytest.raises(ValueError):
        net_native.gcm_seal(b"\x00" * 24, b"\x00" * 12, b"", b"")


# -- plain-UDP sweep: one recvmmsg crossing vs the scalar fallback ------------


def _sweep_drain(method_name: str, payloads):
    """Bind a fresh loopback socket, blast payloads at it, drain with the
    named sweep entry point (small max_pkts so the multi-sweep resume
    path is exercised); return (txn bytes in order, final counters)."""
    import socket
    import time

    nc = net_native.NetClient(max_conns=1, reasm_depth=1)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.bind(("127.0.0.1", 0))
        s.setblocking(False)
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            for p in payloads:
                tx.sendto(p, s.getsockname())
        finally:
            tx.close()
        sweep = getattr(nc, method_name)
        txns = []
        deadline = time.monotonic() + 60
        while (int(nc.counters()["udp_pkts"]) < len(payloads)
               and time.monotonic() < deadline):
            sweep(s.fileno(), 3)
            n = nc.out_count()
            txns.extend(nc.out_txn(i) for i in range(n))
            nc.out_pop(n)
        return txns, nc.counters()
    finally:
        s.close()
        nc.close()


def test_udp_sweep_scalar_vs_scatter_byte_identical():
    """The recvmmsg scatter path and the per-datagram recv fallback must
    deliver the same txn stream and counters over the same load — the
    MTU-stride gaps scatter leaves in the arena are layout, not
    protocol."""
    sizes = (1, 17, 200, 1232, 900, 1232, 64)
    payloads = [bytes([i + 1]) * sz for i, sz in enumerate(sizes)]
    payloads.insert(3, b"J" * 1400)  # > MTU: dropped + counted, no row
    sc_txns, sc_cnt = _sweep_drain("udp_sweep", payloads)
    fb_txns, fb_cnt = _sweep_drain("udp_sweep_scalar", payloads)
    assert sc_txns == fb_txns
    assert [len(t) for t in sc_txns] == list(sizes)
    for key in ("udp_pkts", "oversz"):
        assert sc_cnt[key] == fb_cnt[key], key
    assert sc_cnt["oversz"] == 1
    assert sc_cnt["udp_pkts"] == len(payloads)


def test_udp_ingress_scalar_toggle_parity(monkeypatch):
    """FDTPU_NET_SCALAR_RECV=1 pins UdpIngressStage to the scalar sweep;
    both stage configurations publish identical frames and metrics."""
    import time

    from firedancer_tpu.runtime.net import UdpIngressStage, send_txns
    from firedancer_tpu.tango import shm

    pool = [bytes([i + 1]) * sz
            for i, sz in enumerate((8, 300, 1232, 96))]

    def drive(scalar: bool):
        monkeypatch.setenv("FDTPU_NATIVE_NET", "1")
        monkeypatch.setenv("FDTPU_NET_SCALAR_RECV", "1" if scalar else "0")
        uid = f"{os.getpid()}_{int(time.monotonic_ns() % 1_000_000)}"
        link = shm.ShmLink.create(f"fdtpu_sw{int(scalar)}_{uid}",
                                  depth=64, mtu=1232)
        sink = shm.Consumer(link, lazy=8)
        st = UdpIngressStage("net", outs=[shm.Producer(link)], rx_burst=8)
        assert st._net_client is not None
        try:
            send_txns(st.addr, pool + [b"Z" * 1300])  # oversize rides along
            got = []
            deadline = time.monotonic() + 60
            while ((len(got) < len(pool)
                    or st.metrics.get("oversize_drop") < 1)
                   and time.monotonic() < deadline):
                st.run_once()
                res = sink.poll()
                if isinstance(res, tuple):
                    got.append(bytes(res[1]))
            return got, st.metrics.get("oversize_drop")
        finally:
            st.close()
            link.close()
            link.unlink()

    scatter = drive(False)
    scalar = drive(True)
    assert scatter[0] == scalar[0] == pool
    assert scatter[1] == scalar[1] == 1
