"""Differential suite for the native bank sweep client (ISSUE 16,
native/fd_bank.cpp + runtime/bank_native.py).

Lane parity is the contract: the same microblock stream through the
native sweep lane (fdr_sweep: C-side frame parse, fd_exec_batch2
session exec, PoH-mixin entry build, credit-gated entry/done publish in
ONE crossing) and through the Python after_frag path must publish
byte-identical entry frames in the same order, commit the same funk
state (identical sealed bank hash), and count the same landings.

The cold-account protocol is exercised implicitly: C-built requests
ship all accounts have=0, so the first touch of every payer/dest punts
to the Python resume lane, which ships the values into the session —
steady state is all-native (asserted via the bank_mb_native counter).

The module SKIPS (never fails) without the toolchain or with
FDTPU_NATIVE_BANK=0.
"""

from __future__ import annotations

import os

import pytest

from firedancer_tpu.protocol import txn as ft
from firedancer_tpu.runtime import bank_native as bn
from firedancer_tpu.runtime.bank import BankStage, default_bank_ctx
from firedancer_tpu.runtime.benchg import gen_transfer_pool
from firedancer_tpu.runtime.verify import encode_verified
from firedancer_tpu.tango import shm

if not bn.available():
    pytest.skip(
        "native bank client unavailable (no toolchain or"
        " FDTPU_NATIVE_BANK=0)",
        allow_module_level=True,
    )


def _frag(payload: bytes) -> bytes:
    desc = ft.txn_parse(payload)
    assert desc is not None
    return encode_verified(payload, desc)


def _mb_frame(mb_seq: int, payloads: list[bytes]) -> bytes:
    out = bytearray()
    out += mb_seq.to_bytes(4, "little")
    out += len(payloads).to_bytes(2, "little")
    for p in payloads:
        f = _frag(p)
        out += len(f).to_bytes(2, "little")
        out += f
    return bytes(out)


@pytest.fixture(scope="module")
def frames():
    # dests rotate over 8 keys so the session warms quickly: the first
    # microblocks punt on cold accounts, the tail goes fully native
    pool = gen_transfer_pool(96, n_dests=8)
    return [_mb_frame(i, pool[i * 8 : (i + 1) * 8]) for i in range(12)]


def _drive(frames, *, native: bool, out_depth=256, done_depth=256,
           in_depth=64, lossy=False, iters=20000, bank_idx=3):
    """One BankStage over real rings; returns (armed?, entry frames
    [(payload, sig, tsorig)...], done frames, metrics, bank hash)."""
    prev = os.environ.get(bn.ENV_SWITCH)
    os.environ[bn.ENV_SWITCH] = "1" if native else "0"
    uid = shm.fresh_uid()
    lin = shm.ShmLink.create(f"tbn_i_{uid}", depth=in_depth, mtu=65536,
                             n_fseq=1)
    lpoh = shm.ShmLink.create(f"tbn_p_{uid}", depth=out_depth, mtu=65536,
                              n_fseq=1)
    ldone = shm.ShmLink.create(f"tbn_d_{uid}", depth=done_depth, mtu=64,
                               n_fseq=1)
    try:
        prod = shm.make_producer(lin)
        ctx = default_bank_ctx()
        st = BankStage(
            "b0", ins=[shm.make_consumer(lin, lazy=8)],
            outs=[shm.make_producer(lpoh), shm.make_producer(ldone)],
            bank_idx=bank_idx, ctx=ctx,
        )
        st.require_credit = True
        if lossy:
            from firedancer_tpu.tango.lossy import LossyConsumer
            from firedancer_tpu.utils.rng import Rng

            # a fault-free splice: forces the per-frag fallback path
            st.ins[0] = LossyConsumer(st.ins[0], Rng(7))
        armed = st._sweep_client is not None
        cpoh = shm.make_consumer(lpoh, lazy=4)
        cdone = shm.make_consumer(ldone, lazy=4)
        ents, dones, fed = [], [], 0
        for _ in range(iters):
            while fed < len(frames) and prod.try_publish(
                    frames[fed], sig=fed, tsorig=1000 + fed):
                fed += 1
            st.run_once()
            for cons, acc in ((cpoh, ents), (cdone, dones)):
                while True:
                    r = cons.poll()
                    if r in (shm.POLL_EMPTY, shm.POLL_OVERRUN):
                        break
                    meta, payload = r
                    acc.append((bytes(payload), int(meta[1]),
                                int(meta[5])))
            if fed == len(frames) and len(dones) == len(frames):
                break
        st.flush()
        for cons, acc in ((cpoh, ents), (cdone, dones)):
            while True:
                r = cons.poll()
                if r in (shm.POLL_EMPTY, shm.POLL_OVERRUN):
                    break
                meta, payload = r
                acc.append((bytes(payload), int(meta[1]), int(meta[5])))
        st.during_housekeeping()  # copy the C counters
        rep = {k: st.metrics.get(k) for k in (
            "txn_exec", "txn_exec_failed", "txn_rejected", "microblocks",
            "bank_mb_seen", "bank_mb_native", "bank_mb_stashed",
            "bank_txn_native", "bank_credit_waits", "bank_mb_dropped")}
        bank_hash = ctx.seal(b"\x11" * 32).bank_hash
        return armed, ents, dones, rep, bank_hash
    finally:
        if prev is None:
            os.environ.pop(bn.ENV_SWITCH, None)
        else:
            os.environ[bn.ENV_SWITCH] = prev
        lin.close()
        lpoh.close()
        ldone.close()


def test_stream_diff_native_vs_python(frames):
    a_n, ent_n, done_n, rep_n, h_n = _drive(frames, native=True)
    a_p, ent_p, done_p, rep_p, h_p = _drive(frames, native=False)
    assert a_n and not a_p
    # entry frames byte-identical: payloads (mixin + txns), sigs
    # (mb_seq), tsorigs, order
    assert [(e[0], e[1]) for e in ent_n] == [(e[0], e[1]) for e in ent_p]
    assert len(done_n) == len(done_p) == len(frames)
    assert all(d[0] == b"" and d[1] == 3 for d in done_n)
    for k in ("txn_exec", "txn_exec_failed", "txn_rejected",
              "microblocks"):
        assert rep_n[k] == rep_p[k], k
    assert rep_n["microblocks"] == len(frames)
    assert h_n == h_p  # identical committed state


def test_cold_punts_then_steady_state_native(frames):
    """Cold accounts punt exactly once (all-have=0 requests), then the
    session knows them: the stream's tail must run fully native."""
    armed, _, _, rep, _ = _drive(frames, native=True)
    assert armed
    assert rep["bank_mb_seen"] == len(frames)
    assert rep["bank_mb_stashed"] >= 1   # cold prefix punted
    assert rep["bank_mb_native"] >= len(frames) // 2  # warm tail native
    assert rep["bank_mb_native"] + rep["bank_mb_stashed"] == len(frames)
    assert rep["bank_txn_native"] >= 1
    assert rep["bank_mb_dropped"] == 0


def test_mixed_lane_splice_matches_sweep(frames):
    """A LossyConsumer splice (chaos shape) drops the stage to the
    per-frag path; entries and state must still match the pure sweep."""
    a_s, ent_s, done_s, rep_s, h_s = _drive(frames, native=True)
    a_m, ent_m, done_m, rep_m, h_m = _drive(frames, native=True,
                                            lossy=True)
    assert a_s and a_m
    assert [(e[0], e[1]) for e in ent_s] == [(e[0], e[1]) for e in ent_m]
    assert len(done_s) == len(done_m)
    assert rep_s["txn_exec"] == rep_m["txn_exec"]
    assert h_s == h_m


def test_credit_stall_no_loss_no_reorder(frames):
    """Out rings far smaller than the stream: the C side stalls on
    credits pre-exec (stash, not drop), the Python drain defers until
    the consumers free credits, and every entry still lands in order."""
    a_n, ent_n, done_n, rep_n, h_n = _drive(
        frames, native=True, out_depth=4, done_depth=4, in_depth=16)
    assert a_n
    assert len(done_n) == len(frames)
    assert rep_n["microblocks"] == len(frames)
    assert rep_n["bank_mb_dropped"] == 0
    # entry sigs are mb_seqs, strictly increasing (ring order held)
    sigs = [e[1] for e in ent_n]
    assert sigs == sorted(sigs)
    # byte-identical to the python lane under the same pressure
    _, ent_p, done_p, rep_p, h_p = _drive(
        frames, native=False, out_depth=4, done_depth=4, in_depth=16)
    assert [(e[0], e[1]) for e in ent_n] == [(e[0], e[1]) for e in ent_p]
    assert h_n == h_p


def test_ineligible_txn_splices_in_order(frames):
    """A native-ineligible txn (unknown program) mid-stream punts its
    microblock to the Python resume lane; order and state parity hold."""
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.runtime.benchg import pool_blockhash, pool_payers

    pool = gen_transfer_pool(96, n_dests=8)
    sec, pub = pool_payers()[0]
    msg = ft.message_build(
        version=ft.VLEGACY, signature_cnt=1, readonly_signed_cnt=0,
        readonly_unsigned_cnt=1, acct_addrs=[pub, b"\x07" * 32],
        recent_blockhash=pool_blockhash(),
        instrs=[ft.InstrSpec(program_id=1, accounts=bytes([0]),
                             data=b"\x01")],
    )
    alien = ft.txn_assemble([ref.sign(sec, msg)], msg)
    mbs = list(frames[:6])
    mbs.append(_mb_frame(6, pool[48:52] + [alien]))
    mbs.append(_mb_frame(7, pool[56:64]))
    a_n, ent_n, done_n, rep_n, h_n = _drive(mbs, native=True)
    a_p, ent_p, done_p, rep_p, h_p = _drive(mbs, native=False)
    assert a_n and not a_p
    assert [(e[0], e[1]) for e in ent_n] == [(e[0], e[1]) for e in ent_p]
    assert len(done_n) == len(done_p) == len(mbs)
    assert rep_n["txn_exec"] == rep_p["txn_exec"]
    assert rep_n["txn_rejected"] == rep_p["txn_rejected"]
    assert h_n == h_p


def test_env_switch_disarms():
    os.environ[bn.ENV_SWITCH] = "0"
    try:
        assert not bn.available()
    finally:
        os.environ[bn.ENV_SWITCH] = "1"
    assert bn.available()
