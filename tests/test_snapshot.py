"""Snapshot container tests: zstd tar + append-vec round trips,
incremental overlay, corruption detection, runtime integration."""

import hashlib

import pytest

from firedancer_tpu.flamenco import runtime as rt
from firedancer_tpu.flamenco import snapshot as snap
from firedancer_tpu.funk import Funk


def _fund(funk, tag, lamports, **kw):
    key = hashlib.sha256(tag).digest()
    funk.rec_insert(None, key, rt.acct_build(lamports, **kw))
    return key


def test_full_snapshot_roundtrip(tmp_path):
    funk = Funk()
    k1 = _fund(funk, b"a", 111)
    k2 = _fund(funk, b"b", 222, data=b"hello", owner=b"P" * 32)
    k3 = _fund(funk, b"c", 0, executable=True, data=b"elf!")
    path = str(tmp_path / "snap.tar.zst")
    n = snap.snapshot_write(funk, path, slot=42, bank_hash=b"H" * 32)
    assert n == 3

    funk2, man = snap.snapshot_load(path)
    assert (man.slot, man.bank_hash, man.account_cnt) == (42, b"H" * 32, 3)
    for k in (k1, k2, k3):
        assert funk2.rec_query(None, k) == funk.rec_query(None, k)


def test_incremental_snapshot(tmp_path):
    funk = Funk()
    k1 = _fund(funk, b"x", 10)
    _fund(funk, b"y", 20)
    full = str(tmp_path / "full.tar.zst")
    snap.snapshot_write(funk, full, slot=100)
    _, base_accounts = snap.snapshot_read(full)

    # mutate one account, add another
    funk.rec_insert(None, k1, rt.acct_build(99))
    k3 = _fund(funk, b"z", 30)
    inc = str(tmp_path / "inc.tar.zst")
    n = snap.snapshot_write(
        funk, inc, slot=105, base=base_accounts, base_slot=100
    )
    assert n == 2  # only the changed + the new account

    funk2, man = snap.snapshot_load(full, incremental_path=inc)
    assert man.slot == 105 and man.base_slot == 100
    assert rt.acct_lamports(funk2.rec_query(None, k1)) == 99
    assert rt.acct_lamports(funk2.rec_query(None, k3)) == 30
    assert funk2.rec_cnt_root() == 3

    # loading the incremental as a full snapshot is refused
    with pytest.raises(snap.SnapshotError, match="full snapshot required"):
        snap.snapshot_load(inc)
    # mismatched base slot is refused
    funk3 = Funk()
    _fund(funk3, b"q", 1)
    other = str(tmp_path / "other.tar.zst")
    snap.snapshot_write(funk3, other, slot=999)
    with pytest.raises(snap.SnapshotError, match="incremental base"):
        snap.snapshot_load(other, incremental_path=inc)


def test_corrupt_account_detected(tmp_path):
    import io
    import tarfile

    funk = Funk()
    _fund(funk, b"v", 7, data=b"data!")
    path = str(tmp_path / "c.tar.zst")
    snap.snapshot_write(funk, path, slot=1)
    # the module's own codec shim: exercises whichever compression this
    # host writes (zstd, or the gzip fallback on zstd-less boxes)
    raw = snap._decompress(open(path, "rb").read())
    # flip one byte inside the accounts member
    buf = io.BytesIO(raw)
    out = io.BytesIO()
    with tarfile.open(fileobj=buf) as tin, tarfile.open(
        fileobj=out, mode="w"
    ) as tout:
        for m in tin.getmembers():
            body = tin.extractfile(m).read()
            if m.name.startswith("accounts/"):
                body = bytearray(body)
                # first data byte (after 48B StoredMeta + 56B AccountMeta
                # + 32B hash); the tail bytes are alignment padding the
                # hash deliberately excludes
                body[136] ^= 1
                body = bytes(body)
            info = tarfile.TarInfo(m.name)
            info.size = len(body)
            tout.addfile(info, io.BytesIO(body))
    open(path, "wb").write(snap._compress(out.getvalue(), 3))
    with pytest.raises(snap.SnapshotError, match="hash mismatch"):
        snap.snapshot_read(path)


def test_snapshot_resumes_execution(tmp_path):
    """Boot-from-snapshot: restore, then execute a block on top."""
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.protocol import txn as ft

    funk = Funk()
    secret = hashlib.sha256(b"payer-snap").digest()
    payer = ref.public_key(secret)
    funk.rec_insert(None, payer, rt.acct_build(1_000_000))
    path = str(tmp_path / "boot.tar.zst")
    snap.snapshot_write(funk, path, slot=10)

    funk2, man = snap.snapshot_load(path)
    t = ft.transfer_txn(secret, b"d" * 32, 100, b"B" * 32, from_pubkey=payer)
    res = rt.execute_block(funk2, slot=man.slot + 1, txns=[t], publish=True)
    assert res.results[0].status == rt.TXN_SUCCESS
    assert rt.acct_lamports(funk2.rec_query(None, b"d" * 32)) == 100


def test_incremental_records_deletions(tmp_path):
    """An account removed after the full base must NOT resurrect when
    the incremental overlays it on restore."""
    funk = Funk()
    kd = _fund(funk, b"doomed", 50)
    _fund(funk, b"keeper", 60)
    full = str(tmp_path / "f.tar.zst")
    snap.snapshot_write(funk, full, slot=10)
    _, base_accounts = snap.snapshot_read(full)

    funk.rec_remove(None, kd)
    inc = str(tmp_path / "i.tar.zst")
    snap.snapshot_write(funk, inc, slot=12, base=base_accounts, base_slot=10)

    funk2, man = snap.snapshot_load(full, incremental_path=inc)
    assert kd in man.deleted
    assert funk2.rec_query(None, kd) is None
    assert funk2.rec_cnt_root() == 1
