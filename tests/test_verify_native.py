"""Differential suite for the native verify sweep client (ISSUE 13,
native/fd_verify.cpp + runtime/verify_native.py).

Lane parity is the contract: the same txn stream through the native
sweep lane (fdr_sweep: C-side parse/guards/dedup/batch assembly, one
crossing per sweep) and through the Python intake path must publish
byte-identical verified frames in the same order, with the same
metrics.  Everything here runs with precomputed masks — the lanes under
test are the HOST orchestration, not the device kernel — so no XLA
compile is paid.

The module SKIPS (never fails) without the .so or with
FDTPU_NATIVE_VERIFY=0.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from firedancer_tpu.runtime import verify_native as vn
from firedancer_tpu.runtime.benchg import gen_transfer_pool
from firedancer_tpu.runtime.verify import VerifyStage
from firedancer_tpu.tango import shm

if not vn.available():
    pytest.skip(
        "native verify client unavailable (no toolchain or"
        " FDTPU_NATIVE_VERIFY=0)",
        allow_module_level=True,
    )


@pytest.fixture(scope="module")
def pool():
    return gen_transfer_pool(96, n_payers=12, n_dests=64)


def _drive(stream, *, native: bool, batch=16, max_msg_len=256,
           out_depth=256, drain=True, iters=30000, lossy=False,
           max_inflight=None):
    """One VerifyStage over real rings; returns (stage armed?, frames
    [(payload, sig, tsorig)...], metrics dict, undelivered count)."""
    prev = os.environ.get(vn.ENV_SWITCH)
    os.environ[vn.ENV_SWITCH] = "1" if native else "0"
    uid = shm.fresh_uid()
    lin = shm.ShmLink.create(f"tvn_i_{uid}", depth=256, mtu=1232, n_fseq=1)
    lout = shm.ShmLink.create(f"tvn_o_{uid}", depth=out_depth, mtu=4096,
                              n_fseq=1)
    try:
        prod = shm.make_producer(lin)
        st = VerifyStage(
            "v0", ins=[shm.make_consumer(lin, lazy=8)],
            outs=[shm.make_producer(lout)], batch=batch,
            max_msg_len=max_msg_len, batch_deadline_s=0.001,
            precomputed_ok=True,
            **({"max_inflight": max_inflight} if max_inflight else {}),
        )
        if lossy:
            from firedancer_tpu.tango.lossy import LossyConsumer
            from firedancer_tpu.utils.rng import Rng

            # a fault-free splice: forces the per-frag fallback path
            st.ins[0] = LossyConsumer(st.ins[0], Rng(7))
        armed = st._sweep_client is not None
        cons = shm.make_consumer(lout, lazy=4)
        outs, fed = [], 0
        for _ in range(iters):
            while fed < len(stream) and prod.try_publish(
                    stream[fed], sig=fed, tsorig=1000 + fed):
                fed += 1
            st.run_once()
            if drain:
                while True:
                    r = cons.poll()
                    if r in (shm.POLL_EMPTY, shm.POLL_OVERRUN):
                        break
                    meta, payload = r
                    outs.append((bytes(payload), int(meta[1]),
                                 int(meta[5])))
            if fed == len(stream) and not drain:
                break
        st.flush()
        while True:
            r = cons.poll()
            if r in (shm.POLL_EMPTY, shm.POLL_OVERRUN):
                break
            meta, payload = r
            outs.append((bytes(payload), int(meta[1]), int(meta[5])))
        rep = {k: st.metrics.get(k) for k in (
            "frags_in", "filtered", "txn_verified", "parse_fail",
            "dedup_dup", "msg_too_long", "too_many_sigs", "batches",
            "batch_elems", "intake_dropped", "emit_dropped")}
        return armed, outs, rep, len(stream) - fed
    finally:
        if prev is None:
            os.environ.pop(vn.ENV_SWITCH, None)
        else:
            os.environ[vn.ENV_SWITCH] = prev
        lin.close()
        lout.close()


def _adversarial(pool):
    """Honest txns + a tcache-window duplicate + malformed bytes."""
    stream = list(pool[:40])
    stream.insert(10, pool[9])  # duplicate inside the 16-deep tcache
    stream.append(b"\x01" + b"garbage" * 12)  # malformed
    stream.append(b"")  # empty frag
    return stream


def test_stream_diff_native_vs_python(pool):
    stream = _adversarial(pool)
    a_n, out_n, rep_n, und_n = _drive(stream, native=True)
    a_p, out_p, rep_p, und_p = _drive(stream, native=False)
    assert a_n and not a_p
    assert und_n == und_p == 0
    assert rep_n["dedup_dup"] == rep_p["dedup_dup"] == 1
    assert rep_n["parse_fail"] == rep_p["parse_fail"] == 2
    assert rep_n == rep_p
    assert out_n == out_p  # byte-identical frames, sigs, tsorigs, order


def test_msg_len_guard_parity(pool):
    # a max_msg_len below the txn message size: both lanes drop all
    stream = list(pool[:8])
    a_n, out_n, rep_n, _ = _drive(stream, native=True, max_msg_len=64)
    a_p, out_p, rep_p, _ = _drive(stream, native=False, max_msg_len=64)
    assert a_n
    assert rep_n["msg_too_long"] == rep_p["msg_too_long"] == 8
    assert out_n == out_p == []


def test_mixed_lane_splice_matches_sweep(pool):
    """A LossyConsumer splice (chaos shape) drops the stage to the
    per-frag path, which forwards into the SAME C-side state — frames
    must still match the pure-sweep run."""
    stream = list(pool[:32])
    a_s, out_s, rep_s, _ = _drive(stream, native=True)
    a_m, out_m, rep_m, _ = _drive(stream, native=True, lossy=True)
    assert a_s and a_m
    assert out_s == out_m
    assert rep_s["txn_verified"] == rep_m["txn_verified"]


def test_backpressure_retries_without_loss_or_reorder(pool):
    """An out ring far smaller than the stream: emits stall on credits,
    the frame tables retry next credit window, nothing drops, order
    holds."""
    stream = list(pool)
    armed, outs, rep, und = _drive(stream, native=True, out_depth=16,
                                   batch=8)
    assert armed
    assert und == 0
    assert rep["intake_dropped"] == 0 and rep["emit_dropped"] == 0
    assert len(outs) == len(stream)
    assert [o[2] for o in outs] == sorted(o[2] for o in outs)
    # frames byte-identical to the python lane under the same pressure
    _, outs_p, _, _ = _drive(stream, native=False, out_depth=16, batch=8)
    assert outs == outs_p


def test_stalled_consumer_backpressures_intake(pool):
    """No consumer progress at all: slots fill, the sweep gate closes,
    the INPUT ring backpressures the producer — verified work is never
    dropped — and everything flows once draining resumes."""
    stream = list(pool)
    uid = shm.fresh_uid()
    # input ring much smaller than the stream: a stalled verify must
    # push the pressure back to the producer, not absorb-and-drop
    lin = shm.ShmLink.create(f"tvb_i_{uid}", depth=32, mtu=1232, n_fseq=1)
    lout = shm.ShmLink.create(f"tvb_o_{uid}", depth=8, mtu=4096, n_fseq=1)
    try:
        prod = shm.make_producer(lin)
        st = VerifyStage(
            "v2", ins=[shm.make_consumer(lin, lazy=8)],
            outs=[shm.make_producer(lout)], batch=4, max_msg_len=256,
            batch_deadline_s=0.0005, precomputed_ok=True, max_inflight=2)
        assert st._sweep_client is not None
        fed = 0
        for _ in range(4000):  # consumer never drains
            while fed < len(stream) and prod.try_publish(
                    stream[fed], sig=fed, tsorig=1000 + fed):
                fed += 1
            st.run_once()
        assert fed < len(stream)  # the producer felt the stall
        assert st.metrics.get("intake_dropped") == 0
        # resume draining: every fed txn arrives, in order, then the
        # rest of the stream flows through cleanly
        cons = shm.make_consumer(lout, lazy=4)
        outs = []
        for _ in range(30000):
            while fed < len(stream) and prod.try_publish(
                    stream[fed], sig=fed, tsorig=1000 + fed):
                fed += 1
            st.run_once()
            while True:
                r = cons.poll()
                if r in (shm.POLL_EMPTY, shm.POLL_OVERRUN):
                    break
                meta, payload = r
                outs.append((bytes(payload), int(meta[1]), int(meta[5])))
            if fed == len(stream) and len(outs) >= len(stream):
                break
        st.flush()
        while True:
            r = cons.poll()
            if r in (shm.POLL_EMPTY, shm.POLL_OVERRUN):
                break
            meta, payload = r
            outs.append((bytes(payload), int(meta[1]), int(meta[5])))
        assert len(outs) == len(stream)
        assert [o[2] for o in outs] == [1000 + i
                                        for i in range(len(stream))]
    finally:
        lin.close()
        lout.close()


def test_client_counters_surface_in_metrics(pool):
    stream = list(pool[:24])
    _, _, rep, _ = _drive(stream, native=True)
    assert rep["frags_in"] == 24
    assert rep["txn_verified"] == 24
    assert rep["batches"] >= 1 and rep["batch_elems"] == 24


def test_env_switch_disarms():
    os.environ[vn.ENV_SWITCH] = "0"
    try:
        assert not vn.available()
    finally:
        os.environ[vn.ENV_SWITCH] = "1"
    assert vn.available()


def test_shard_filter_in_sweep(pool):
    """shard_cnt=2: the C callback filters by seq parity exactly like
    before_frag, and the filtered count matches."""
    uid = shm.fresh_uid()
    lin = shm.ShmLink.create(f"tvs_i_{uid}", depth=128, mtu=1232, n_fseq=1)
    lout = shm.ShmLink.create(f"tvs_o_{uid}", depth=128, mtu=4096,
                              n_fseq=1)
    try:
        prod = shm.make_producer(lin)
        st = VerifyStage(
            "v1", ins=[shm.make_consumer(lin, lazy=8)],
            outs=[shm.make_producer(lout)], batch=8, max_msg_len=256,
            batch_deadline_s=0.001, precomputed_ok=True,
            shard_idx=1, shard_cnt=2)
        assert st._sweep_client is not None
        for i, p in enumerate(pool[:20]):
            prod.publish(p, sig=i)
        for _ in range(200):
            st.run_once()
        st.flush()
        st.during_housekeeping()  # copy the C counters
        assert st.metrics.get("filtered") == 10
        assert st.metrics.get("txn_verified") == 10
    finally:
        lin.close()
        lout.close()
