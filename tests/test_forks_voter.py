"""choreo forks (bank frontier) + voter (vote-txn emission), and the
consensus loop wiring: replay -> forks -> ghost -> tower -> voter ->
vote txns the runtime's vote program executes."""

import pytest

from firedancer_tpu.choreo import Forks, ForkError, Ghost, Voter
from firedancer_tpu.funk import Funk
from firedancer_tpu.flamenco import runtime as rt
from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.protocol import txn as ft


def test_forks_insert_freeze_frontier():
    f = Forks(0)
    f.insert(1, 0)
    with pytest.raises(ForkError, match="not frozen"):
        f.insert(2, 1)  # parent 1 not executed yet
    f.freeze(1, xid=b"x1", bank_hash=b"h" * 32, poh_hash=b"p" * 32)
    f.insert(2, 1)
    f.insert(3, 1)  # competing fork off slot 1
    f.freeze(2, xid=b"x2", bank_hash=b"h" * 32, poh_hash=b"p" * 32)
    f.freeze(3, xid=b"x3", bank_hash=b"h" * 32, poh_hash=b"p" * 32)
    tips = sorted(x.slot for x in f.frontier())
    assert tips == [2, 3]
    assert f.is_ancestor(1, 3) and not f.is_ancestor(2, 3)


def test_forks_duplicate_and_bad_parent():
    f = Forks(0)
    f.insert(5, 0)
    with pytest.raises(ForkError, match="already exists"):
        f.insert(5, 0)
    with pytest.raises(ForkError, match="unknown fork"):
        f.insert(7, 6)
    f.freeze(5, xid=b"x", bank_hash=b"h" * 32, poh_hash=b"p" * 32)
    with pytest.raises(ForkError, match="<= parent"):
        f.insert(4, 5)


def test_forks_publish_prunes_losers():
    f = Forks(0)
    for slot, parent in [(1, 0), (2, 1), (3, 1), (4, 2)]:
        f.insert(slot, parent)
        f.freeze(slot, xid=b"x%d" % slot, bank_hash=b"h" * 32,
                 poh_hash=b"p" * 32)
    pruned = f.publish(2)
    # loser fork 3 pruned; retired ancestors 0,1 gone; 2 is root, 4 kept
    assert pruned == [0, 1, 3]
    assert f.root_slot == 2
    assert 4 in f and 3 not in f
    with pytest.raises(ForkError):
        f.publish(3)


def test_voter_emits_and_respects_lockout():
    secret = bytes(range(32))
    pub = ref.public_key(secret)
    vote_acct = b"V" * 32
    f = Forks(0)
    for slot, parent in [(1, 0), (2, 1)]:
        f.insert(slot, parent)
        f.freeze(slot, xid=b"x", bank_hash=b"h" * 32, poh_hash=b"p" * 32)
    v = Voter(vote_account=vote_acct, voter_pubkey=pub,
              sign=lambda m: ref.sign(secret, m))
    bh = b"B" * 32
    t1 = v.maybe_vote(1, bh, is_ancestor=f.is_ancestor)
    assert t1 is not None
    parsed = ft.txn_parse(t1)
    assert parsed is not None
    # no double/backwards vote
    assert v.maybe_vote(1, bh, is_ancestor=f.is_ancestor) is None
    t2 = v.maybe_vote(2, bh, is_ancestor=f.is_ancestor)
    assert t2 is not None

    # a conflicting fork at slot 3 (off 1): locked out by the vote on 2
    f.insert(3, 1)
    f.freeze(3, xid=b"x", bank_hash=b"h" * 32, poh_hash=b"p" * 32)
    assert v.maybe_vote(3, bh, is_ancestor=f.is_ancestor) is None


def test_consensus_loop_end_to_end():
    """Votes flow: voter txn -> runtime vote program -> ghost weights ->
    head selection -> forks.publish at the tower root."""
    from firedancer_tpu.flamenco import agave_state as ast
    from firedancer_tpu.flamenco import vote_program as vp

    secret = bytes(range(32))
    pub = ref.public_key(secret)
    vote_acct = b"V" * 32

    funk = Funk()
    funk.rec_insert(None, pub, rt.acct_build(10_000_000))
    init = ast.VoteState(node_pubkey=pub, authorized_withdrawer=pub,
                         authorized_voters={0: pub})
    funk.rec_insert(None, vote_acct, rt.acct_build(
        0,
        data=ast.vote_state_encode(init).ljust(vp.VOTE_STATE_SIZE, b"\x00"),
        owner=ft.VOTE_PROGRAM,
    ))

    ghost = Ghost(0)
    forks = Forks(0, root_xid=None)
    voter = Voter(vote_account=vote_acct, voter_pubkey=pub,
                  sign=lambda m: ref.sign(secret, m))

    # a vote for slot N is validated against SlotHashes (N's bank hash)
    # and lands in slot N+1 — the real one-slot lag
    parent_hash = b"\x00" * 32
    parent_xid = None
    slot_hashes = []
    pending_vote = None
    for slot in (1, 2, 3):
        ghost.insert(slot, slot - 1)
        forks.insert(slot, slot - 1)
        res = rt.execute_block(
            funk, slot=slot,
            txns=[pending_vote] if pending_vote is not None else [],
            parent_bank_hash=parent_hash, parent_xid=parent_xid,
            slot_hashes=list(slot_hashes),
        )
        if pending_vote is not None:
            assert res.results[0].status == rt.TXN_SUCCESS
        forks.freeze(slot, xid=res.xid, bank_hash=res.bank_hash,
                     poh_hash=b"p" * 32)
        slot_hashes.append((slot, res.bank_hash))
        pending_vote = voter.maybe_vote(
            slot, b"B" * 32, is_ancestor=forks.is_ancestor,
            bank_hash=res.bank_hash,
        )
        assert pending_vote is not None
        ghost.vote(pub, slot, 1_000)
        parent_hash, parent_xid = res.bank_hash, res.xid

    assert ghost.head() == 3
    from firedancer_tpu.flamenco.executor import acct_decode

    vote_data = acct_decode(funk.rec_query(parent_xid, vote_acct))[3]
    vs = ast.vote_state_decode(vote_data)
    # votes for slots 1 and 2 landed (slot 3's vote is still pending)
    assert [(v.lockout.slot, v.lockout.confirmation_count)
            for v in vs.votes] == [(1, 2), (2, 1)]

    pruned = forks.publish(1)
    assert 0 in pruned and forks.root_slot == 1
